"""Sentry — the per-peer misbehavior ledger behind peer quarantine.

Hashgraph's BFT claim covers up to 1/3 *malicious* validators, but the
protocol layer only ever *refuses* hostile input — it never remembers who
sent it. The sentry closes that loop (docs/robustness.md §Byzantine fault
model):

- every classified ingest rejection (typed errors from
  ``hashgraph/errors.py`` — wrong-key signatures, fabricated parents,
  unknown creators, oversized syncs, forks) adds a weighted score to the
  offending peer's record;
- scores decay exponentially (half-life ``decay_halflife_s``) so an
  isolated hiccup — or an honest peer briefly caught relaying a fork's
  descendants — is forgiven, while a sustained flood is not;
- crossing ``threshold`` puts the peer in **time-boxed quarantine**: the
  gossip selector skips it and inbound syncs from it are refused until
  ``quarantine_s`` elapses, after which the slate is wiped and the peer
  is re-admitted (a falsely-flagged peer recovers on its own);
- **equivocation proofs** are kept separately and forever: a
  :class:`ForkError` carries two signed events at the same
  (creator, index) with different hashes — cryptographic evidence that
  survives restarts via the store's evidence table and is served at the
  ``/suspects`` endpoint.

Scoring is attributed carefully: a fork is scored against the event's
*creator* (honest peers can innocently relay a fork's branches), while
everything else is scored against the *direct sender* (an honest peer
verifies events before relaying, so a wrong-key event can only come from
the node that made it up).

**Trust model caveat**: the RPC envelope's ``from_id`` is NOT
authenticated (same as the reference), so sender-attributed scores are
*advisory* — an attacker can frame an honest id or rotate ids to dodge
its own score. Four properties bound the damage: fork quarantine keys
on *signed* evidence (spoof-proof); unproven-cause quarantines are
capped at the BFT bound f = ⌊(N−1)/3⌋ simultaneously (the framing
guard — more than f peers "misbehaving" at once is framing by
definition, and the selector additionally keeps a liveness floor if its
whole view is quarantined); quarantine is time-boxed with score decay,
so a framed honest peer recovers on its own; and quarantine is a
cost-shedding layer on top of the actual safety checks
(signature/parent/fork verification runs on every event regardless), so
evading it buys the attacker nothing but the full price of per-event
rejection. The per-peer ledger is bounded (MAX_RECORDS, and the
quarantine cap keeps quarantined — unevictable — records to ~f) so
id-rotation cannot balloon memory.

The sentry carries its own narrow lock — it is touched from gossip worker
threads and RPC handlers that deliberately do not hold the core lock.
``clock`` is injectable for deterministic tests.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..common.clock import WALL
from ..common.timed_lock import named_lock
from ..config.config import (
    DEFAULT_SENTRY_DECAY_HALFLIFE_S,
    DEFAULT_SENTRY_QUARANTINE_S,
    DEFAULT_SENTRY_THRESHOLD,
)
from ..crypto.canonical import jsonable as _jsonable
from ..hashgraph.errors import (
    ForkError,
    InvalidSignatureError,
    classify_rejection,
)
from ..hashgraph.event import Event, EventBody

# Cause slug -> score added per offence. `fork` lands at the default
# threshold on its own: equivocation is cryptographically proven, so it
# earns no benefit of the doubt. `unknown_creator` stays cheap because
# honest traffic produces it around membership-change races, and
# `unknown_parent` because honest laggards produce it around
# fast-forward evictions. `garbage` is not emitted by the classifier —
# garbage payloads surface as unknown_creator/unknown_parent — it is the
# reserved weight for directly-recorded offences (tools, tests, future
# transport-level classification).
DEFAULT_WEIGHTS: Dict[str, float] = {
    "fork": 8.0,
    "invalid_signature": 2.0,
    "oversized_sync": 2.0,
    "garbage": 2.0,
    "unknown_creator": 1.0,
    "unknown_parent": 0.25,
}

# Bound on the per-peer ledger: from_id is attacker-controlled, so a
# hostile flood of fresh ids must not grow _records without limit.
MAX_RECORDS = 4096

# Bound on durable proofs per equivocating creator: ONE conflicting
# signed pair is already conclusive; a persistent equivocator forking at
# every new height must not grow the proof ledger (memory, evidence
# table, /suspects payload) without limit. Creators are bounded by the
# repertoire (forks only decode for registered validators), so total
# proofs ≤ N × this.
MAX_PROOFS_PER_CREATOR = 8


@dataclass
class EquivocationProof:
    """A signed (event A, event B) pair at the same (creator, index) with
    different hashes — self-contained, independently verifiable evidence
    of equivocation. Serialized as plain JSON so it can ride the store's
    evidence table and the ``/suspects`` endpoint unchanged."""

    creator: str  # event.creator() form ("0X…" encoded pub key)
    index: int
    event_a: dict  # {"Body": …, "Signature": …}, bytes already b64
    event_b: dict
    observed_at: int  # wall-clock seconds (int: proofs ride canonical JSON)

    def key(self) -> str:
        """One proof per forked slot: later conflicting pairs at the same
        (creator, index) are duplicates of the same offence."""
        return f"{self.creator}:{self.index}"

    @staticmethod
    def from_events(
        existing: Event, incoming: Event, observed_at: Optional[float] = None
    ) -> "EquivocationProof":
        def pack(e: Event) -> dict:
            return _jsonable({"Body": e.body.to_dict(), "Signature": e.signature})

        # The production caller (Sentry.observe_rejection) always passes
        # its node clock's wall time, so proofs stamp virtual time under
        # sim and same-seed replays export byte-identical evidence. The
        # bare default — a raw time.time() before the babblelint clock
        # pass caught it — now routes through the WALL abstraction and
        # only serves clockless direct callers (tests, tools).
        return EquivocationProof(
            creator=incoming.creator(),
            index=incoming.index(),
            event_a=pack(existing),
            event_b=pack(incoming),
            observed_at=int(
                observed_at if observed_at is not None else WALL.time()
            ),
        )

    def events(self) -> tuple[Event, Event]:
        def unpack(d: dict) -> Event:
            return Event(
                EventBody.from_dict(d["Body"]), signature=d.get("Signature", "")
            )

        return unpack(self.event_a), unpack(self.event_b)

    def verify(self) -> bool:
        """True iff this really is a fork: both events are signed by the
        claimed creator, sit at the same index, and differ in hash."""
        a, b = self.events()
        return (
            a.creator() == self.creator
            and b.creator() == self.creator
            and a.index() == self.index
            and b.index() == self.index
            and a.hex() != b.hex()
            and a.verify()
            and b.verify()
        )

    def to_dict(self) -> dict:
        return {
            "creator": self.creator,
            "index": self.index,
            "event_a": self.event_a,
            "event_b": self.event_b,
            "observed_at": self.observed_at,
        }

    @staticmethod
    def from_dict(d: dict) -> "EquivocationProof":
        return EquivocationProof(
            creator=d["creator"],
            index=d["index"],
            event_a=d["event_a"],
            event_b=d["event_b"],
            observed_at=int(d.get("observed_at", 0)),
        )


@dataclass
class _PeerRecord:
    """Mutable per-peer ledger entry (guarded by the sentry lock)."""

    score: float = 0.0
    last_update: float = 0.0
    causes: Dict[str, int] = field(default_factory=dict)
    quarantined_until: float = 0.0  # 0 = not quarantined
    quarantines: int = 0
    proven: bool = False  # current quarantine entered on signed evidence


class Sentry:
    """Per-peer misbehavior scores → time-boxed quarantine, plus the
    durable equivocation-proof ledger. See the module docstring."""

    def __init__(
        self,
        threshold: float = DEFAULT_SENTRY_THRESHOLD,
        quarantine_s: float = DEFAULT_SENTRY_QUARANTINE_S,
        decay_halflife_s: float = DEFAULT_SENTRY_DECAY_HALFLIFE_S,
        weights: Optional[Dict[str, float]] = None,
        clock: Callable[[], float] = time.monotonic,
        wall_clock: Callable[[], float] = time.time,
    ):
        self.threshold = threshold
        self.quarantine_s = quarantine_s
        self.decay_halflife_s = decay_halflife_s
        self.weights = dict(DEFAULT_WEIGHTS if weights is None else weights)
        self._clock = clock
        self._wall_clock = wall_clock
        # Named for the BABBLE_LOCKCHECK acquisition-order recorder:
        # ingest rejections score under the core lock, so the
        # core->sentry edge is part of the audited model.
        self._lock = named_lock("sentry")
        self._records: Dict[int, _PeerRecord] = {}
        self._proofs: Dict[str, EquivocationProof] = {}
        self._store = None  # evidence persistence hook (attach_store)
        # Peer-set size, for the BFT framing guard (set_peer_count): at
        # most f = ⌊(N−1)/3⌋ peers can actually be malicious, so a state
        # where MORE than f are simultaneously quarantined on spoofable
        # evidence is framing, not mass misbehavior — such quarantines
        # are deferred (score kept, no quarantine). 0 = guard off.
        self._peer_count = 0
        # counters surfaced through stats()
        self.rejects: Dict[str, int] = {}
        self.quarantines_total = 0
        self.readmissions = 0
        self.refused_rpcs = 0
        self.quarantine_deferrals = 0

    @classmethod
    def from_config(cls, conf) -> "Sentry":
        return cls(
            threshold=conf.sentry_threshold,
            quarantine_s=conf.sentry_quarantine_s,
            decay_halflife_s=conf.sentry_decay_halflife_s,
            # the node clock: quarantine time-boxes and proof timestamps
            # follow virtual time under the sim engine
            clock=conf.clock.monotonic,
            wall_clock=conf.clock.time,
        )

    # -- evidence persistence --------------------------------------------

    def attach_store(self, store) -> None:
        """Wire evidence persistence: proofs recorded from now on are
        written through `store.set_evidence`, and proofs already durable
        there are loaded back — so evidence survives a restart with
        ``--store`` (with or without ``--bootstrap``)."""
        if not hasattr(store, "set_evidence"):
            return
        with self._lock:
            self._store = store
            try:
                for key, data in store.all_evidence().items():
                    if key not in self._proofs:
                        self._proofs[key] = EquivocationProof.from_dict(data)
            except Exception:  # noqa: BLE001 — evidence is advisory
                pass

    # -- scoring -----------------------------------------------------------

    def record(
        self,
        peer_id: int,
        cause: str,
        weight: Optional[float] = None,
        proven: Optional[bool] = None,
    ) -> bool:
        """Add one offence to ``peer_id``'s record; returns True when this
        offence pushed the peer into quarantine. ``proven`` marks an
        offence backed by signed evidence on file (a recorded fork
        proof): proven quarantines bypass — and don't consume — the
        framing-guard f budget. Defaults to ``cause == "fork"`` for
        direct callers; observe_rejection passes the exact
        proof-on-file truth."""
        w = self.weights.get(cause, 1.0) if weight is None else weight
        if proven is None:
            proven = cause == "fork"
        now = self._clock()
        with self._lock:
            self.rejects[cause] = self.rejects.get(cause, 0) + 1
            if peer_id not in self._records and len(self._records) >= MAX_RECORDS:
                self._prune(now)
            rec = self._records.setdefault(peer_id, _PeerRecord())
            self._expire(rec, now)
            rec.score = self._decayed(rec, now) + w
            rec.last_update = now
            rec.causes[cause] = rec.causes.get(cause, 0) + 1
            if rec.score >= self.threshold and rec.quarantined_until <= now:
                # Framing guard: from_id is spoofable, so unproven
                # quarantines are capped at the BFT bound f — an attacker
                # framing honest ids can sideline at most f peers, never
                # the cluster. Signed fork evidence bypasses the cap (it
                # names a registered creator cryptographically, and only
                # N creators exist, so it is bounded anyway).
                if not proven and self._quarantine_cap_reached(now):
                    self.quarantine_deferrals += 1
                    return False
                rec.quarantined_until = now + self.quarantine_s
                rec.proven = proven
                rec.quarantines += 1
                self.quarantines_total += 1
                return True
            return False

    def set_peer_count(self, n: int) -> None:
        """Arm the framing guard with the live validator count (wired by
        Core on init and every peer-set change)."""
        self._peer_count = n

    def _quarantine_cap_reached(self, now: float) -> bool:
        """Only UNPROVEN active quarantines count toward the f cap: a
        fork-proven equivocator sitting in quarantine must not shield a
        concurrent flooder from being quarantined too. The cap is
        max(1, ⌊(N−1)/3⌋) — the floor of 1 is deliberate: in clusters
        so small that the BFT f is 0 (N ≤ 3), a flooder must still be
        quarantinable at the price of one frameable slot."""
        if self._peer_count <= 0:
            return False
        f = max(1, (self._peer_count - 1) // 3)
        active = sum(
            1
            for r in self._records.values()
            if r.quarantined_until > now and not r.proven
        )
        return active >= f

    def observe_rejection(self, err: object, from_id: int) -> Optional[str]:
        """Classify an ingest exception, mint a proof when it is a fork,
        and score the right peer (see the attribution note in the module
        docstring; forks resolve the creator's id via
        ``set_creator_resolver``). Returns the cause slug recorded, or
        None when the error is not the peer's fault."""
        cause = classify_rejection(err)
        if cause is None:
            return None
        target = from_id
        proven = None
        if isinstance(err, ForkError):
            target = self._resolve_creator_id(err.creator, from_id)
            with self._lock:
                already = f"{err.creator}:{err.index}" in self._proofs
            if err.existing is not None and not already:
                # The proof is deduped per forked slot (checked BEFORE
                # paying the canonical-JSON packing — repeat pushes of a
                # known fork hit this path every gossip round), but every
                # re-push still scores: honest relays can't even carry
                # the second branch (known-map gossip tracks only the
                # highest index), so a repeat can only come from the
                # provably-guilty creator itself.
                self.add_proof(
                    EquivocationProof.from_events(
                        err.existing, err.incoming, self._wall_clock()
                    )
                )
            # "proven" (framing-guard bypass + /suspects label) tracks
            # what is actually ON FILE: a fork whose stored branch was
            # already evicted (existing=None) or whose proof write was
            # rejected stays an unproven, f-capped quarantine.
            proven = self._has_proof_for(err.creator)
        elif isinstance(err, InvalidSignatureError) and err.event is not None:
            # A signature failure is ambiguous once a fork is on file:
            # an honest event whose other-parent is the forked creator's
            # event re-hashes against OUR branch and fails verification
            # through no fault of the sender. Reject the event, count
            # the cause, but don't score the (likely honest) relayer.
            if self._fork_adjacent(err.event):
                # counted ONLY under the dedicated slug so
                # sentry_rejects_total still reconciles one-per-rejection
                with self._lock:
                    self.rejects["invalid_signature_fork_adjacent"] = (
                        self.rejects.get("invalid_signature_fork_adjacent", 0)
                        + 1
                    )
                return cause
        self.record(target, cause, proven=proven)
        return cause

    def _has_proof_for(self, creator_hex: str) -> bool:
        with self._lock:
            return any(p.creator == creator_hex for p in self._proofs.values())

    def _fork_adjacent(self, event: Event) -> bool:
        """True when the failed event's parent creators include a creator
        we hold fork evidence for — only FIRST-generation descendants of
        a fork fail with a signature mismatch (deeper ones fail earlier,
        with unknown_parent), so checking the direct parents suffices.
        An attacker can dodge sender-scoring by *claiming* a forked
        creator as other-parent, but only after a fork is already on
        file, and the event is still rejected — the dodge buys immunity
        from a cost-shedding layer, never from the safety checks."""
        with self._lock:
            if not self._proofs:
                return False
            proof_creators = {p.creator for p in self._proofs.values()}
            proof_ids = set()
            for c in proof_creators:
                pid = None
                if self._creator_resolver is not None:
                    try:
                        pid = self._creator_resolver(c)
                    except Exception:  # noqa: BLE001
                        pid = None
                if pid is not None:
                    proof_ids.add(pid)
        if event.creator() in proof_creators:
            return True
        op_cid = event.body.other_parent_creator_id
        return bool(event.other_parent()) and op_cid in proof_ids

    def set_creator_resolver(
        self, resolver: Callable[[str], Optional[int]]
    ) -> None:
        """``resolver(creator_hex) -> peer id`` (or None) — lets fork
        evidence be scored against the equivocator rather than whichever
        honest peer happened to relay the second branch."""
        self._creator_resolver = resolver

    _creator_resolver: Optional[Callable[[str], Optional[int]]] = None

    def _resolve_creator_id(self, creator_hex: str, fallback: int) -> int:
        if self._creator_resolver is not None:
            try:
                pid = self._creator_resolver(creator_hex)
            except Exception:  # noqa: BLE001
                pid = None
            if pid is not None:
                return pid
        return fallback

    def add_proof(self, proof: EquivocationProof) -> bool:
        """Record (and persist) a proof; returns False for a duplicate of
        an already-recorded forked slot, or when the creator already has
        MAX_PROOFS_PER_CREATOR proofs on file (one pair is conclusive —
        a serial forker must not balloon the evidence ledger)."""
        with self._lock:
            if proof.key() in self._proofs:
                return False
            if (
                sum(
                    1
                    for p in self._proofs.values()
                    if p.creator == proof.creator
                )
                >= MAX_PROOFS_PER_CREATOR
            ):
                return False
            self._proofs[proof.key()] = proof
            store = self._store
        if store is not None:
            try:
                store.set_evidence(proof.key(), proof.to_dict())
            except Exception:  # noqa: BLE001 — never let evidence IO
                pass  # failures poison the ingest path
        return True

    def proofs(self) -> List[EquivocationProof]:
        with self._lock:
            return list(self._proofs.values())

    # -- quarantine --------------------------------------------------------

    def is_quarantined(self, peer_id: int) -> bool:
        now = self._clock()
        with self._lock:
            rec = self._records.get(peer_id)
            if rec is None:
                return False
            self._expire(rec, now)
            return rec.quarantined_until > now

    def note_refused(self) -> None:
        """Count an inbound RPC refused because its sender is quarantined."""
        with self._lock:
            self.refused_rpcs += 1

    def _expire(self, rec: _PeerRecord, now: float) -> None:
        """Lazy quarantine expiry: serving out the sentence wipes the
        score, so a falsely-flagged peer re-enters with a clean slate
        (its proofs, if any, remain — evidence is forever)."""
        if 0.0 < rec.quarantined_until <= now:
            rec.quarantined_until = 0.0
            rec.proven = False
            rec.score = 0.0
            rec.last_update = now
            self.readmissions += 1

    def _prune(self, now: float) -> None:
        """Bound the ledger under a fresh-id flood (from_id is
        attacker-controlled): drop decayed-out records first, then the
        lowest scorers — but NEVER a quarantined peer's record, and never
        below MAX_RECORDS/2 so real offenders keep their history."""
        dead = [
            pid
            for pid, rec in self._records.items()
            if rec.quarantined_until <= now and self._decayed(rec, now) < 0.05
        ]
        for pid in dead:
            del self._records[pid]
        if len(self._records) >= MAX_RECORDS:
            evictable = sorted(
                (
                    (self._decayed(rec, now), pid)
                    for pid, rec in self._records.items()
                    if rec.quarantined_until <= now
                ),
            )
            for _, pid in evictable[: len(self._records) - MAX_RECORDS // 2]:
                del self._records[pid]

    def _decayed(self, rec: _PeerRecord, now: float) -> float:
        if rec.score <= 0.0 or self.decay_halflife_s <= 0.0:
            return rec.score
        dt = max(0.0, now - rec.last_update)
        return rec.score * 0.5 ** (dt / self.decay_halflife_s)

    # -- observability -----------------------------------------------------

    def suspects(self) -> dict:
        """The ``/suspects`` payload: live per-peer ledger + proof list
        (docs/robustness.md documents the schema)."""
        now = self._clock()
        with self._lock:
            peers = {}
            for pid, rec in self._records.items():
                self._expire(rec, now)
                peers[str(pid)] = {
                    "score": round(self._decayed(rec, now), 3),
                    "causes": dict(rec.causes),
                    "quarantined": rec.quarantined_until > now,
                    "quarantine_remaining_s": round(
                        max(0.0, rec.quarantined_until - now), 3
                    ),
                    "quarantines": rec.quarantines,
                }
            return {
                "threshold": self.threshold,
                "quarantine_s": self.quarantine_s,
                "decay_halflife_s": self.decay_halflife_s,
                "peers": peers,
                "proofs": [p.to_dict() for p in self._proofs.values()],
            }

    def stats(self) -> Dict[str, object]:
        now = self._clock()
        with self._lock:
            for rec in self._records.values():
                self._expire(rec, now)
            quarantined = sum(
                1
                for rec in self._records.values()
                if rec.quarantined_until > now
            )
            out: Dict[str, object] = {
                "sentry_quarantined_peers": quarantined,
                "sentry_quarantines_total": self.quarantines_total,
                "sentry_quarantine_deferrals": self.quarantine_deferrals,
                "sentry_readmissions": self.readmissions,
                "sentry_refused_rpcs": self.refused_rpcs,
                "sentry_proofs": len(self._proofs),
                "sentry_rejects_total": sum(self.rejects.values()),
            }
            for cause, n in sorted(self.rejects.items()):
                out[f"sentry_rejects_{cause}"] = n
            return out
