"""Gossip heartbeat timer.

Reference semantics: src/node/control_timer.go:11-80 — a background timer
that fires ticks at random intervals in [min, 2*min), can be reset with a
new interval, stopped, and shut down.

Implemented as a thread waiting on a condition variable with timeout
rather than Go channels.
"""

from __future__ import annotations

import random
import threading


class ControlTimer:
    def __init__(self, rng=None) -> None:
        self.tick = threading.Event()
        self._cond = threading.Condition()
        self._interval: float = 0.0
        self._armed = False
        self._shutdown = False
        self.is_set = False
        self._thread: threading.Thread | None = None
        # Jitter source: the node injects Config.seeded_rng("control_timer")
        # so the gossip cadence is a pure function of the master seed —
        # a global-random draw here made same-seed sim replays diverge
        # on the JOINING path (docs/simulation.md determinism contract).
        # None (production) falls back to the process-global module.
        self._rng = rng if rng is not None else random

    def _jitter(self, interval: float) -> float:
        """Random interval in [min, 2*min) — the reference's jittered
        heartbeat, drawn from the injected stream."""
        return interval + self._rng.random() * interval

    def run(self, init_interval: float) -> None:
        """Start the timer loop in the background
        (reference: control_timer.go:47-70)."""
        self.reset(init_interval)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._armed and not self._shutdown:
                    self._cond.wait()
                if self._shutdown:
                    self.is_set = False
                    return
                interval = self._interval
                wait = self._jitter(interval)
                self._armed = False
                notified = self._cond.wait(timeout=wait)
                if self._shutdown:
                    self.is_set = False
                    return
                if self._armed:
                    # reset arrived while waiting: loop with new interval
                    continue
                if notified:
                    # stop() disarmed the timer: no tick
                    # (reference: control_timer.go:62-64 sets timer = nil)
                    continue
            self.is_set = False
            self.tick.set()

    def reset(self, interval: float) -> None:
        """Arm the timer with a new interval (reference: control_timer.go:62)."""
        with self._cond:
            self._interval = interval
            self._armed = True
            self.is_set = True
            self._cond.notify()

    def poke(self) -> None:
        """Wake a ``tick`` waiter WITHOUT a timer fire. The babble loop
        blocks on ``tick`` (event-driven, no poll quantum); suspend and
        shutdown call this so the loop re-checks its exit flags
        immediately instead of waiting out the current interval."""
        self.tick.set()

    def stop(self) -> None:
        with self._cond:
            self._armed = False
            self.is_set = False
            self._cond.notify()

    def shutdown(self) -> None:
        """reference: control_timer.go:73-80."""
        with self._cond:
            self._shutdown = True
            self._cond.notify()
        self.tick.set()
