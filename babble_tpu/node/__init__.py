"""Node runtime: state machine, gossip scheduler, core façade
(reference: src/node/)."""

from .state import State, StateManager
from .validator import Validator
from .core import Core
from .node import Node
from .sentry import EquivocationProof, Sentry

__all__ = [
    "State",
    "StateManager",
    "Validator",
    "Core",
    "Node",
    "Sentry",
    "EquivocationProof",
]
