"""Adaptive gossip scheduler: the control law behind the gossip cadence.

The reference protocol gossips on a fixed two-speed heartbeat (10 ms
busy / 1 s idle, ``control_timer.py``) with exactly ONE partner per
tick. That law is blind to every live signal the node already computes:
how hot the mempool is, how far peers trail us (or we trail them), and
how congested our own ingest pipeline is. The result is the
commit-latency wall ROADMAP item 1 names — under load the node keeps
metronome time while its mempool and its peers' lag say "go faster,
talk to more people", and under ingest overload it keeps soliciting
syncs it cannot insert.

This module is that missing controller. It is deliberately a PURE
control law: no threads, no clocks, no RNG — ``update(signals)`` maps
one signal snapshot to one :class:`GossipPlan` and mutates only the
controller's own smoothing state. That purity is what lets the
deterministic simulation engine (docs/simulation.md) run the SAME
controller under virtual time with byte-identical replays.

Control law (docs/gossip.md §Adaptive scheduling):

- **tempo** (how often to gossip) rises with mempool pressure, with our
  own lag behind peers, and with unfinished consensus work (``busy``);
  the interval lerps from ``slow_s`` (tempo 0) to ``fast_s`` (tempo 1).
- **spread** (how many partners per tick) rises with mempool pressure
  and with how far peers trail US — fan-out only helps when we hold
  events others need.
- **congestion** (our own decode→verify→insert pipeline occupancy)
  brakes both: a node that cannot insert what it already has must stop
  soliciting more, so congestion multiplies the interval back up and
  collapses fan-out toward 1. It also shrinks the pipeline's soft
  depth cap so backpressure reaches senders earlier.
- every raw signal is EWMA-smoothed (``alpha``) and the published
  interval/fan-out only move when the target crosses a **hysteresis**
  band, so the scheduler doesn't flap on tick-to-tick noise.
- outputs are hard-clamped: interval to [fast_s, slow_s], fan-out to
  [1, max_fanout], soft depth to [4, queue_cap].

Kill switch: ``BABBLE_ADAPT=0`` (or ``adaptive_gossip=false``) makes
``Node`` skip constructing the controller entirely and fall back to the
fixed two-speed timer, one partner per tick — the reference's scheduler,
bit for bit. The switch isolates the SCHEDULER only (that is what the
A/B benches compare): coalesced self-event minting and the staged pull
leg keep their own switches (``selfevent_burst=0``,
``gossip_pipeline=false``).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GossipSignals:
    """One snapshot of the live load signals, taken by the node."""

    busy: bool = False          # Core.busy(): unfinished consensus work
    mempool_pending: int = 0    # admitted-not-drained transactions
    inflight: int = 0           # gossip_inflight_syncs (pipeline gauge)
    queue_depth: int = 0        # gossip_pipeline_queue_depth
    peer_behind: int = 0        # max events any peer trails US by
    self_behind: int = 0        # max events WE trail any peer by
    rounds_inflight: int = 0    # OUR gossip rounds still running
    rounds_cap: int = 1         # the node's gossip-slot budget


@dataclass(frozen=True)
class GossipPlan:
    """The controller's verdict for the next tick."""

    interval: float   # seconds until the next gossip tick
    fanout: int       # distinct partners to gossip this tick
    soft_depth: int   # pipeline soft queue cap (backpressure threshold)
    tempo: float      # smoothed demand-for-frequency in [0, 1]
    congestion: float  # smoothed ingest congestion in [0, 1]


class AdaptiveGossipController:
    """Signal → (interval, fan-out, pipeline depth) control law."""

    def __init__(
        self,
        fast_s: float,
        slow_s: float,
        max_fanout: int = 3,
        queue_cap: int = 64,
        inflight_cap: int = 8,
        mempool_hot: int = 1024,
        lag_hot: int = 256,
        alpha: float = 0.4,
        hysteresis: float = 0.15,
        congestion_brake: float = 4.0,
    ):
        if fast_s <= 0 or slow_s < fast_s:
            raise ValueError(
                f"need 0 < fast_s <= slow_s, got {fast_s}/{slow_s}"
            )
        self.fast_s = fast_s
        self.slow_s = slow_s
        self.max_fanout = max(1, int(max_fanout))
        self.queue_cap = max(4, int(queue_cap))
        self.inflight_cap = max(1, int(inflight_cap))
        self.mempool_hot = max(1, int(mempool_hot))
        self.lag_hot = max(1, int(lag_hot))
        self.alpha = min(1.0, max(0.01, alpha))
        self.hysteresis = max(0.0, hysteresis)
        self.congestion_brake = max(0.0, congestion_brake)
        # smoothing state
        self._tempo = 0.0
        self._spread = 0.0
        self._congestion = 0.0
        # published outputs (hysteresis compares targets against these)
        self._interval = slow_s
        self._fanout = 1
        self._soft_depth = self.queue_cap
        # counters (obs catalog: adaptive_*)
        self.ticks = 0
        self.adjustments = 0

    @classmethod
    def from_config(cls, conf) -> "AdaptiveGossipController":
        """Tune the law from the node Config: the fixed timer's two
        speeds become the clamp rails, a full self-event's worth of
        pending transactions is 'hot', and a quarter sync_limit of lag
        is 'far behind' (one pull can heal up to sync_limit)."""
        return cls(
            fast_s=conf.heartbeat_timeout,
            slow_s=max(conf.heartbeat_timeout, conf.slow_heartbeat_timeout),
            max_fanout=conf.gossip_max_fanout,
            queue_cap=conf.gossip_pipeline_depth,
            mempool_hot=conf.mempool_event_max_txs,
            lag_hot=max(64, conf.sync_limit // 4),
        )

    # -- the law --------------------------------------------------------

    def update(self, sig: GossipSignals) -> GossipPlan:
        """Fold one signal snapshot into the smoothed state and return
        the plan for the next tick. Deterministic: same controller
        state + same signals → same plan, always."""
        self.ticks += 1
        mem_p = min(1.0, sig.mempool_pending / self.mempool_hot)
        self_p = min(1.0, sig.self_behind / self.lag_hot)
        peer_p = min(1.0, sig.peer_behind / self.lag_hot)
        tempo_raw = max(1.0 if sig.busy else 0.0, mem_p, self_p)
        spread_raw = max(mem_p, peer_p)
        congestion_raw = max(
            min(1.0, sig.queue_depth / self.queue_cap),
            min(1.0, sig.inflight / self.inflight_cap),
            # our own rounds overrunning the cadence: on a CPU-starved
            # host the ingest queue can look empty while every gossip
            # slot is still occupied at the next tick — fanning out
            # there just thrashes the scheduler. ONE carryover round is
            # exempt: a single round-trip outlasting the tick is the
            # normal pipelined state whenever the network RTT exceeds
            # the heartbeat, not a congestion signal.
            min(
                1.0,
                max(0, sig.rounds_inflight - 1)
                / max(1, sig.rounds_cap - 1),
            ),
        )
        # Demand signals attack fast, decay smoothly: an idle node's
        # first transaction must arm the fast cadence THIS tick, not
        # after the EWMA crawls up through seconds of slow-rail
        # intervals — while a single quiet tick doesn't drop the tempo.
        # Congestion stays symmetric-smooth in BOTH directions: queue
        # depth spikes on every burst, and an instant-rise/slow-decay
        # brake rides those spikes into a near-permanent slowdown
        # (measured: ~3x worse smoke commit p50 than the smooth brake).
        a = self.alpha
        self._tempo = max(
            tempo_raw, self._tempo + a * (tempo_raw - self._tempo)
        )
        self._spread = max(
            spread_raw, self._spread + a * (spread_raw - self._spread)
        )
        self._congestion += a * (congestion_raw - self._congestion)

        # interval: lerp slow→fast on tempo, braked back up by congestion
        target = self.slow_s - (self.slow_s - self.fast_s) * self._tempo
        target *= 1.0 + self.congestion_brake * self._congestion
        target = min(self.slow_s, max(self.fast_s, target))
        # absorbing rails: a target inside the hysteresis band of a rail
        # IS the rail — saturated regimes publish the exact clamp value
        # instead of parking an off-rail residue inside the band
        if target <= self.fast_s * (1.0 + self.hysteresis):
            target = self.fast_s
        elif target >= self.slow_s * (1.0 - self.hysteresis):
            target = self.slow_s
        # fan-out: spread wants more partners, congestion collapses it
        fan_exact = 1.0 + (self.max_fanout - 1) * self._spread * max(
            0.0, 1.0 - self._congestion
        )
        # soft pipeline depth: congested nodes backpressure earlier
        depth_exact = self.queue_cap * (1.0 - 0.75 * self._congestion)

        changed = False
        # hysteresis: republish the interval only when the target moved
        # by more than the band (relative), fan-out only when the exact
        # value crosses the half step plus the band. The clamp rails
        # always publish exactly — converging to "almost fast" would
        # leave a permanent off-rail residue inside the band.
        if target != self._interval and (
            abs(target - self._interval) > self.hysteresis * self._interval
            or target in (self.fast_s, self.slow_s)
        ):
            self._interval = target
            changed = True
        fan_target = int(fan_exact + 0.5)
        if fan_target != self._fanout and (
            abs(fan_exact - self._fanout) > 0.5 + self.hysteresis
        ):
            self._fanout = min(self.max_fanout, max(1, fan_target))
            changed = True
        depth_target = max(4, min(self.queue_cap, int(depth_exact + 0.5)))
        if depth_target != self._soft_depth and (
            abs(depth_target - self._soft_depth)
            > max(2, int(self.hysteresis * self.queue_cap))
            or depth_target in (4, self.queue_cap)  # absorbing rails
        ):
            self._soft_depth = depth_target
            changed = True
        if changed:
            self.adjustments += 1
        return GossipPlan(
            interval=self._interval,
            fanout=self._fanout,
            soft_depth=self._soft_depth,
            tempo=self._tempo,
            congestion=self._congestion,
        )

    # -- observability --------------------------------------------------

    def current(self) -> GossipPlan:
        """The last published plan, without folding new signals."""
        return GossipPlan(
            interval=self._interval,
            fanout=self._fanout,
            soft_depth=self._soft_depth,
            tempo=self._tempo,
            congestion=self._congestion,
        )

    def stats(self) -> dict:
        return {
            "adaptive_interval_ms": round(1e3 * self._interval, 3),
            "adaptive_fanout": self._fanout,
            "adaptive_soft_depth": self._soft_depth,
            "adaptive_ticks": self.ticks,
            "adaptive_adjustments": self.adjustments,
        }
