"""Node state machine primitives.

Reference semantics: src/node/state/state.go:10-101 — six states, an
atomically-updated current state, and a bounded pool of background
routines (WGLIMIT=20) that can be waited on.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable, List


class State(enum.IntEnum):
    """reference: state/state.go:10-36."""

    BABBLING = 0
    CATCHING_UP = 1
    JOINING = 2
    LEAVING = 3
    SHUTDOWN = 4
    SUSPENDED = 5

    def __str__(self) -> str:
        return {
            State.BABBLING: "Babbling",
            State.CATCHING_UP: "CatchingUp",
            State.JOINING: "Joining",
            State.LEAVING: "Leaving",
            State.SHUTDOWN: "Shutdown",
            State.SUSPENDED: "Suspended",
        }[self]


# Maximum concurrently running background routines
# (reference: state/state.go:41).
WGLIMIT = 20


class StateManager:
    """Current state + bounded background-routine pool
    (reference: state/state.go:62-101)."""

    def __init__(self) -> None:
        self._state = State.BABBLING
        self._state_lock = threading.Lock()
        self._routines_lock = threading.Lock()
        self._routines: List[threading.Thread] = []
        self._live = 0

    def get_state(self) -> State:
        with self._state_lock:
            return self._state

    def set_state(self, s: State) -> None:
        with self._state_lock:
            self._state = s

    def go_func(self, f: Callable[[], None]) -> bool:
        """Run f on a background thread if fewer than WGLIMIT are live;
        returns False when the task was declined at the cap
        (reference: state/state.go:86-97; live count mirrors its wgCount
        atomic rather than scanning threads)."""

        def wrapped() -> None:
            try:
                f()
            finally:
                with self._routines_lock:
                    self._live -= 1

        with self._routines_lock:
            if self._live >= WGLIMIT:
                return False
            self._live += 1
            if len(self._routines) >= WGLIMIT:
                self._routines = [t for t in self._routines if t.is_alive()]
            t = threading.Thread(target=wrapped, daemon=True)
            try:
                t.start()
            except Exception:
                # wrapped() never ran, so undo its accounting here or the
                # counter saturates and declines work forever.
                self._live -= 1
                return False
            self._routines.append(t)
        return True

    def wait_routines(self, timeout: float = 10.0) -> None:
        """Wait up to ``timeout`` total for live background routines
        (reference: state/state.go:99-101).

        Deliberately WALL time, not the node clock (audited for the
        babblelint clock pass, docs/static_analysis.md): the routines
        are real OS threads even under sim, and ``Thread.join`` blocks
        in wall time — a virtual deadline would never advance while
        joining and hang shutdown."""
        from ..common.clock import WALL

        deadline = WALL.monotonic() + timeout
        with self._routines_lock:
            routines = list(self._routines)
        for t in routines:
            remaining = deadline - WALL.monotonic()
            if remaining <= 0:
                break
            t.join(timeout=remaining)
