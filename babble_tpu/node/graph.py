"""Read-only extraction of the whole hashgraph for visualization /
debugging (reference: /root/reference/src/node/graph.go:8-127)."""

from __future__ import annotations

from typing import Dict, List

from ..crypto.canonical import jsonable


class Graph:
    """Wraps a Node and dumps participant events, rounds, and blocks."""

    def __init__(self, node) -> None:
        self.node = node

    def participant_events(self) -> Dict[str, Dict[str, dict]]:
        """participant -> event hex -> event dict (graph.go:18-55)."""
        store = self.node.core.hg.store
        out: Dict[str, Dict[str, dict]] = {}
        for pub in store.repertoire_by_pub_key():
            evs: Dict[str, dict] = {}
            try:
                hashes = store.participant_events(pub, -1)
            except Exception:
                hashes = []
            for h in hashes:
                try:
                    ev = store.get_event(h)
                except Exception:
                    continue
                evs[h] = {
                    "Body": jsonable(ev.body.to_dict()),
                    "Signature": ev.signature,
                    "Round": ev.round,
                    "LamportTimestamp": ev.lamport_timestamp,
                }
            out[pub] = evs
        return out

    def rounds(self) -> List[dict]:
        """All round infos in order (graph.go:57-77)."""
        store = self.node.core.hg.store
        out = []
        for i in range(store.last_round() + 1):
            try:
                out.append(
                    jsonable(store.get_round(i).to_dict())
                )
            except Exception:
                out.append(None)
        return out

    def blocks(self) -> List[dict]:
        """All blocks in order (graph.go:79-99)."""
        store = self.node.core.hg.store
        out = []
        for i in range(store.last_block_index() + 1):
            try:
                out.append(
                    jsonable(store.get_block(i).to_dict())
                )
            except Exception:
                out.append(None)
        return out

    def to_dict(self) -> dict:
        """The /graph payload (graph.go:110-127)."""
        return {
            "ParticipantEvents": self.participant_events(),
            "Rounds": self.rounds(),
            "Blocks": self.blocks(),
        }
