"""Node: the top-level actor running the gossip state machine.

Reference semantics: src/node/node.go — Init picks the starting state
(:128-164), Run dispatches on state (:168-199), doBackgroundWork drains
the transport and submit queues (:341-361), babble() gossips on timer
ticks (:416-463), gossip = pull + push (:466-615), fastForward (:622-701),
join (:709-751), suspend (:384-408); RPC handlers in src/node/node_rpc.go.

Threading model: one background worker thread (transport consumer +
submit queue), one state-machine thread, gossip rounds on the bounded
routine pool, all hashgraph access serialized by core_lock — mirroring
the reference's coreLock discipline (node.go:35).
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Dict, List, Optional

from ..config.config import Config
from ..hashgraph.errors import is_normal_self_parent_error
from ..hashgraph.event import WireEvent
from ..hashgraph.internal_transaction import InternalTransaction
from ..hashgraph.store import Store
from ..net.rpc import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    JoinRequest,
    JoinResponse,
    RPC,
    SyncRequest,
    SyncResponse,
)
from ..net.transport import RemoteError, Transport, TransportError
from ..obs.provenance import parse_ctx
from ..peers.peer import Peer
from ..peers.peer_set import PeerSet
from ..common.latency import LatencyRecorder
from ..common import lockcheck
from ..common.timed_lock import TimedLock
from ..proxy.proxy import AppProxy
from .control_timer import ControlTimer
from .core import Core, PreparedSync
from .state import State, StateManager
from .validator import Validator

logger = logging.getLogger(__name__)


class Node(StateManager):
    """reference: node/node.go:22-75."""

    def __init__(
        self,
        conf: Config,
        validator: Validator,
        peers: PeerSet,
        genesis_peers: PeerSet,
        store: Store,
        trans: Transport,
        proxy: AppProxy,
    ):
        super().__init__()
        self.conf = conf
        self.logger = conf.logger("node")
        # THE node's time source (common/clock.py): every deadline,
        # sleep, and duration measurement below reads through this one
        # handle, so the sim engine can swap in virtual time wholesale.
        self.clock = conf.clock
        from ..mempool import Mempool
        from .sentry import Sentry

        selector_rng = conf.seeded_rng("selector", validator.id())
        # Jitter stream for the join/fast-forward retry backoffs below;
        # None (production) lets backoff draw from the global random.
        self._backoff_rng = conf.seeded_rng("backoff", validator.id())
        self.core = Core(
            validator,
            peers,
            genesis_peers,
            store,
            proxy.commit_block,
            conf.maintenance_mode,
            accelerated_verify=conf.accelerator,
            accelerator_mesh=conf.accelerator_mesh,
            mempool=Mempool.from_config(conf),
            sentry=Sentry.from_config(conf),
            clock=self.clock,
            selector_rng=selector_rng,
            selfevent_burst=conf.selfevent_burst,
        )
        # Equivocation proofs persist through the store's evidence table
        # (and load back on restart) when the store supports it.
        self.core.sentry.attach_store(store)
        # Telemetry: the core created its registry (docs/observability.md);
        # bind the node-level instruments (RPC counters, queue depth) and
        # take the sync-stage observer for the gossip legs below.
        self.telemetry = self.core.obs
        # Instrumented core lock: get_stats surfaces total acquisition
        # wait (lock_wait_ms_total) so lock-shrinking work stays measured;
        # contended waits also feed the core_lock_wait_seconds histogram.
        self.core_lock = TimedLock(
            observer=self.telemetry.lock_wait_observer,
            clock=self.clock.perf_counter,
            name="core",  # BABBLE_LOCKCHECK order recorder (lockcheck.py)
        )
        self.trans = trans
        self.proxy = proxy
        self.submit_q = proxy.submit_queue()
        # Synchronous admission: a proxy that supports it hands SubmitTx
        # straight to the mempool (its own lock, never the core lock) and
        # returns the verdict to the client; the queue below stays as the
        # fallback for proxies predating verdicts.
        if hasattr(proxy, "set_submit_handler"):
            proxy.set_submit_handler(self._admit_transaction)
        # Jitter stream for the heartbeat timer: seeded under sim so the
        # gossip cadence replays byte-identically (babblelint clock pass
        # caught the old global-random draw; docs/static_analysis.md).
        self.control_timer = ControlTimer(
            rng=conf.seeded_rng("control_timer", validator.id())
        )
        self.shutdown_event = threading.Event()
        self.suspend_event = threading.Event()
        self._threads: List[threading.Thread] = []
        self.start_time = 0.0
        self.sync_requests = 0
        self.sync_errors = 0
        # Per-RPC-type handler error counters (surfaced as rpc_errors_* in
        # get_stats): chaos runs use these to tell "request dropped by the
        # nemesis" (no counter moves) from "handler crashed" (it does).
        self.rpc_errors: Dict[str, int] = {
            "sync": 0,
            "eager_sync": 0,
            "fast_forward": 0,
            "join": 0,
        }
        # Receiving-side sync_limit enforcement: batches above our own
        # configured cap are truncated (both the eager-push handler and
        # the pull response) — a hostile peer must not dictate how much
        # we ingest per request.
        self.sync_limit_truncations = 0
        # Sender-side twin: OUR diff exceeded sync_limit and was cut
        # before the push. A peer that chronically trails by more than
        # one sync_limit shows up here — silent truncation was how the
        # lag hid (ISSUE 11 satellite).
        self.sync_diff_truncations = 0
        # Outbound gossip rounds lost to TransportErrors — the network-
        # fault counter the chaos soaks assert on (rpc_errors_* counts
        # handler crashes, this counts the wire).
        self.gossip_transport_errors = 0
        # Causal tracing (docs/observability.md §Causal tracing):
        # inbound RPCs carrying a wire trace context, and the last
        # SUCCESSFUL outbound gossip round (node clock, monotonic) — the
        # stall watchdog's gossip-liveness signal.
        self.trace_ctx_rpcs = 0
        self.last_gossip_ok: Optional[float] = None
        # Provenance knobs ride the Config; the table itself was built
        # by the core's NodeTelemetry (so standalone cores trace too).
        self.telemetry.provenance.configure(
            sample=conf.trace_sample, cap=conf.trace_table_cap
        )
        # Stall flight recorder (obs/flight.py): armed by run(), fired
        # when a busy node stops making consensus progress.
        from ..obs.flight import StallWatchdog

        self.watchdog = StallWatchdog(
            self,
            stall_s=conf.watchdog_stall_s,
            interval_s=conf.watchdog_interval_s,
            out_dir=conf.flight_dir,
        )
        # Joining-state backoff: consecutive join failures grow the retry
        # sleep exponentially (capped by conf.join_backoff_cap) so a node
        # stuck outside a partitioned cluster doesn't hammer dead peers.
        self._join_failures = 0
        # Gossip-leg durations, served at /debug/timers (the reference logs
        # the same ns durations per round, node.go:511-514,543-548,593-608).
        self.timers = LatencyRecorder()
        self.initial_undetermined_events = 0
        self._prewarm_thread = None
        # Cap overlapping gossip rounds: unbounded overlap just piles
        # threads onto core_lock under the GIL (the Go reference relies on
        # cheap goroutines; here a small in-flight cap keeps the pipeline
        # full). Sized for the adaptive fan-out: a full-fan tick plus one
        # straggler from the previous tick.
        self._gossip_slot_cap = max(2, conf.gossip_max_fanout + 1)
        self._gossip_slots = threading.Semaphore(self._gossip_slot_cap)
        # Rounds currently occupying a slot, and the tick-start snapshot
        # of it (rounds still running FROM THE PREVIOUS tick) — the
        # adaptive controller's "our own gossip is overrunning the
        # cadence" congestion signal. The snapshot, not the live value:
        # sampled right after a fan-out spawn the live count is trivially
        # high and would brake a perfectly healthy node.
        self._gossip_rounds_inflight = 0
        self._rounds_carryover = 0
        self._rounds_lock = threading.Lock()
        # Adaptive gossip scheduler (node/adaptive.py, docs/gossip.md
        # §Adaptive scheduling): maps live load signals to the next
        # tick's interval / fan-out / pipeline soft depth. None (the
        # BABBLE_ADAPT=0 kill switch or adaptive_gossip=false) falls
        # back to the fixed two-speed heartbeat, bit for bit.
        self.adaptive = None
        if conf.adaptive_gossip:
            from .adaptive import AdaptiveGossipController

            self.adaptive = AdaptiveGossipController.from_config(conf)
        self._plan_lock = threading.Lock()
        self._fanout = 1
        # Last stateful controller fold (monotonic): _reset_timer runs
        # after EVERY handled RPC, and each fold moves the EWMAs — so
        # folds are rate-limited to one per fast-rail interval, or an
        # RPC burst would collapse the smoothing exactly when it
        # matters. Between folds the published plan is reused.
        self._last_plan_t = float("-inf")
        # Per-peer lag from exchanged known-maps (healthview's
        # advance-rate idea moved into the node): how many events each
        # peer trails us by, and how many we trail them by — the
        # adaptive controller's spread/tempo signals.
        self._lag_lock = threading.Lock()
        self._peer_behind: Dict[int, int] = {}
        self._self_behind: Dict[int, int] = {}
        # Inbound-sync pipeline (node/pipeline.py): decode+batch-verify
        # overlap across handler threads, the insert tail drains through
        # one serialized inserter, bounded queue backpressures the
        # transport. Wall-clock only — the deterministic sim engine
        # drives _process_rpc single-threaded under virtual time, where
        # a background inserter would break replay determinism.
        from ..common.clock import WALL

        self.pipeline = None
        if conf.gossip_pipeline and self.clock is WALL:
            from .pipeline import SyncPipeline

            self.pipeline = SyncPipeline(
                self, queue_cap=conf.gossip_pipeline_depth
            )
        # Light-client gateway tier (docs/clients.md): the tx→block
        # proof index always runs (GET /proof/<txid> works on any node
        # with a service); the SubscriptionHub binds only when
        # --client-listen is set AND the node runs on the wall clock
        # (the sim engine drives commits deterministically and must not
        # grow a socket thread).
        from ..client.proofs import TxIndex

        self.txindex = TxIndex(conf.txindex_cap)
        # Index commits only when something can serve reads — a
        # subscription hub or the HTTP service (GET /proof). A pure
        # validator (--no-service, no --client-listen) skips the
        # per-commit sha256 walk and the index memory entirely; sim
        # clusters (no_service=True) keep their commit path lean too.
        self._txindex_enabled = bool(conf.client_listen) or not conf.no_service
        self.proofs_served = 0
        self.proof_misses = 0
        self.checkpoint_exports = 0
        # Lifecycle tier (docs/lifecycle.md): checkpoint-prune compaction,
        # driven from the gossip/monologue tails (_maybe_prune). Off by
        # default — prune_every_rounds=0 keeps the store append-only.
        self.pruner = None
        if conf.prune_every_rounds > 0:
            from ..lifecycle.pruner import CheckpointPruner

            self.pruner = CheckpointPruner(
                every_rounds=conf.prune_every_rounds,
                keep_rounds=conf.prune_keep_rounds,
                vacuum=conf.prune_vacuum,
            )
        # /checkpoint requests rejected for falling below the prune floor
        # (clients see the behind_retention slug, not a generic 404).
        self.behind_retention_rejections = 0
        # Store-footprint snapshot memo: size_stats on a persistent store
        # runs COUNT(*) queries, so the stats surface re-reads it at most
        # once a second.
        self._size_stats_memo: Dict[str, object] = {"t": -1.0, "v": None}
        self.client_hub = None
        if conf.client_listen and self.clock is WALL:
            from ..client.subhub import SubscriptionHub

            self.client_hub = SubscriptionHub(
                conf.client_listen,
                block_source=self.get_sealed_block,
                moniker=conf.moniker,
                queue_frames=conf.sub_queue_frames,
                stall_timeout_s=conf.sub_stall_timeout_s,
                shed_lag=conf.sub_shed_lag,
                sndbuf=conf.sub_sndbuf,
                clock=self.clock,
            )
            self.client_hub.listen()
        self.core.commit_listeners.append(self._on_commit_block)
        self.telemetry.bind_node(self)

    # -- lifecycle ----------------------------------------------------------

    def init(self) -> None:
        """Pick the initial state (reference: node.go:128-164)."""
        if self.conf.accelerator:
            # Resolve the device first: if the TPU link is down the probe
            # times out and the accelerated path runs on host XLA instead
            # of wedging the node at its first jax call.
            import os

            from babble_tpu.ops.device import (
                ensure_device,
                is_cpu_fallback,
                jax_usable,
            )

            ensure_device()

            mesh_req = getattr(self.core, "accelerator_mesh", 0)
            if mesh_req > 1 and jax_usable() and self.core.hg.accel is not None:
                # Multi-chip sweeps: build the mesh only now, after the
                # probe has ruled out a wedged device link.
                from babble_tpu.parallel.mesh import consensus_mesh

                if mesh_req & (mesh_req - 1):
                    # W buckets are powers of two, so a non-power-of-two
                    # mesh could never divide any window — it would be
                    # reported but never used. Refuse it loudly instead.
                    self.logger.warning(
                        "--accelerator-mesh %d is not a power of two; no "
                        "witness bucket would ever shard over it — "
                        "running single-device",
                        mesh_req,
                    )
                else:
                    try:
                        self.core.hg.accel.mesh = consensus_mesh(mesh_req)
                    except Exception:
                        self.logger.warning(
                            "--accelerator-mesh %d unavailable (fewer "
                            "devices?); sweeps run single-device",
                            mesh_req,
                            exc_info=True,
                        )
            if not is_cpu_fallback():
                # Pre-warm the voting-sweep shape buckets a fresh node is
                # likely to hit (background thread; XLA compiles with the
                # GIL released, and the persistent compilation cache makes
                # warm restarts near-instant). Without this the first real
                # backlog meets a compile wait and the oracle carries it.
                # BABBLE_PREWARM_BLOCK=1 makes init wait for the warm-up
                # (bench harnesses: compiles tracing in Python would
                # otherwise contend with the measured gossip).
                from babble_tpu.hashgraph.accel import prewarm_buckets

                self._prewarm_thread = prewarm_buckets(
                    len(self.core.peers.peers),
                    mesh=self.core.hg.accel.mesh
                    if self.core.hg.accel is not None
                    else None,
                )
                if (
                    os.environ.get("BABBLE_PREWARM_BLOCK") == "1"
                    and self._prewarm_thread is not None
                ):
                    self._prewarm_thread.join(timeout=300.0)

            if (
                os.environ.get("BABBLE_DEVICE_VERIFY") == "1"
                and jax_usable()
                and not is_cpu_fallback()
            ):
                # Device signature verification is opt-in (measured ~90x
                # slower than the native verifier through the tunnel); when
                # forced, compile its kernel before gossip starts.
                from babble_tpu.ops.verify import warmup

                warmup()
        if self.conf.bootstrap:
            self.core.bootstrap()
            with self.core_lock:
                self.core.set_head_and_seq()

        if not self.conf.maintenance_mode:
            self.trans.listen()
            if self.core.validator.id() in self.core.peers.by_id:
                self._set_babbling_or_catching_up_state()
            else:
                self._transition(State.JOINING)
        else:
            self._transition(State.SUSPENDED)

        self.initial_undetermined_events = len(self.core.get_undetermined_events())

    def run(self, gossip: bool = True) -> None:
        """Main loop (reference: node.go:168-199)."""
        if self.conf.maintenance_mode:
            return
        self.start_time = self.clock.monotonic()
        if self.telemetry.enabled:
            # flight recorder (no-op when watchdog_stall_s <= 0); only
            # the threaded production path arms the monitor — the sim
            # harness drives nodes without run() and calls check() itself
            self.watchdog.start()
            # always-on sampling profiler (obs/profile.py): ONE
            # process-wide sampler shared by co-located nodes, reading
            # thread stacks only — safe to arm from any node, off under
            # BABBLE_OBS=0 or profile_hz=0
            from ..obs import profile as obs_profile

            obs_profile.ensure_started(self.conf.profile_hz)
        self.control_timer.run(self.conf.heartbeat_timeout)
        bg = threading.Thread(target=self._do_background_work, daemon=True)
        bg.start()
        self._threads.append(bg)

        while True:
            state = self.get_state()
            if state == State.BABBLING:
                self._babble(gossip)
            elif state == State.CATCHING_UP:
                self._fast_forward()
            elif state == State.JOINING:
                self._join()
            elif state == State.SUSPENDED:
                self.clock.sleep(0.2)
            elif state == State.SHUTDOWN:
                return
            else:
                self.clock.sleep(0.05)

    def run_async(self, gossip: bool = True) -> None:
        t = threading.Thread(target=self.run, args=(gossip,), daemon=True)
        t.start()
        self._threads.append(t)

    def leave(self) -> None:
        """Politely leave the network (reference: node.go:207-224)."""
        if self.conf.maintenance_mode:
            return
        try:
            self.core.leave(self.conf.join_timeout, lock=self.core_lock)
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        """reference: node.go:228-246."""
        if self.get_state() != State.SHUTDOWN:
            self.logger.info("SHUTDOWN")
            self._transition(State.SHUTDOWN)
            self.shutdown_event.set()
            self.watchdog.stop()
            if self.pipeline is not None:
                self.pipeline.stop()
            if self.client_hub is not None:
                self.client_hub.close()
            self.control_timer.shutdown()
            self.wait_routines(timeout=2.0)
            if self.trans is not None:
                self.trans.close()
            self.core.hg.store.close()

    def suspend(self) -> None:
        """Stop gossiping but keep answering sync requests
        (reference: node.go:250-262)."""
        if self.get_state() not in (State.SUSPENDED, State.SHUTDOWN):
            self.logger.info("SUSPEND")
            self._transition(State.SUSPENDED)
            self.suspend_event.set()
            # the babble loop blocks on the tick event (no poll): wake
            # it so the suspend is observed now, not next heartbeat
            self.control_timer.poke()
            self.wait_routines(timeout=2.0)

    # -- getters ------------------------------------------------------------

    def get_id(self) -> int:
        return self.core.validator.id()

    def get_pub_key(self) -> str:
        return self.core.validator.public_key_hex()

    def get_block(self, index: int):
        return self.core.hg.store.get_block(index)

    def get_last_block_index(self) -> int:
        return self.core.get_last_block_index()

    def get_last_consensus_round_index(self) -> int:
        lcr = self.core.get_last_consensus_round_index()
        return -1 if lcr is None else lcr

    def get_peers(self) -> List[Peer]:
        return self.core.peers.peers

    def get_validator_set(self, round: int) -> List[Peer]:
        return self.core.hg.store.get_peer_set(round).peers

    def get_all_validator_sets(self) -> Dict[int, List[Peer]]:
        return self.core.hg.store.get_all_peer_sets()

    # -- light-client gateway tier (docs/clients.md) -------------------------

    def _on_commit_block(self, block) -> None:
        """Core commit listener: index the block's transactions for
        proofs (when a read surface exists) and advance the subscription
        hub's head watermark (O(1); the hub encodes and pushes from its
        own thread)."""
        if self._txindex_enabled:
            self.txindex.index_block(block)
        if self.client_hub is not None:
            self.client_hub.publish(block.index())

    def get_sealed_block(self, index: int):
        """Block ``index`` once SEALED — carrying MORE than 1/3
        validator signatures, the bar the anchor logic and every
        stateless verifier use — else None. The hub re-polls Nones, so
        subscribers see each block as soon as enough signatures have
        gossiped in, in order, with no gaps."""
        if index < 0 or index > self.core.get_last_block_index():
            return None
        try:
            block = self.core.hg.store.get_block(index)
            peer_set = self.core.hg.store.get_peer_set(
                block.round_received()
            )
        except Exception:  # noqa: BLE001 — evicted/missing: not servable
            return None
        if len(block.signatures) <= peer_set.trust_count():
            return None
        return block

    def get_proof(self, txid: str) -> Optional[Dict[str, object]]:
        """Signed Merkle inclusion proof for one committed transaction
        (GET /proof/<txid>; None = unknown/aged out → 404). Signatures
        accumulate for a round or two after commit — a proof fetched
        too early simply carries fewer of them and the client retries."""
        from ..client.proofs import build_proof

        loc = self.txindex.lookup(txid)
        if loc is None:
            self.proof_misses += 1
            return None
        try:
            block = self.core.hg.store.get_block(loc[0])
        except Exception:  # noqa: BLE001 — block aged out of the store
            self.proof_misses += 1
            return None
        self.proofs_served += 1
        return build_proof(block, loc[1])

    def get_checkpoint(
        self, at_round: Optional[int] = None, with_snapshot: bool = False
    ) -> Dict[str, object]:
        """Signed fast-sync checkpoint (GET /checkpoint): the anchor
        block + its frame. Raises ValueError while no block is sealed
        yet. ``at_round`` asks for coverage from a specific round: the
        earliest sealed block received at-or-after it. Below the prune
        floor that history is compacted away — BehindRetentionError,
        served as the distinct ``behind_retention`` slug so clients
        ratchet forward instead of retrying forever. ``with_snapshot``
        embeds the app snapshot at the anchor so a REJOINING VALIDATOR
        can proxy.restore before fast_forward (replicas don't need it;
        reference ships the same payload in FastForwardResponse)."""
        from ..client.checkpoint import make_checkpoint
        from ..lifecycle.pruner import BehindRetentionError

        with self.core_lock:
            floor = self.core.hg.prune_floor
            if at_round is not None and floor is not None and at_round < floor:
                self.behind_retention_rejections += 1
                raise BehindRetentionError(requested=at_round, floor=floor)
            if at_round is None:
                block, frame = self.core.get_anchor_block_with_frame()
            else:
                block = self._sealed_block_at_round(at_round)
                if block is None:
                    raise ValueError(
                        f"no sealed block at or after round {at_round}"
                    )
                frame = self.core.hg.get_frame(block.round_received())
            snapshot = None
            if with_snapshot:
                snapshot = self.proxy.get_snapshot(block.index())
            cp = make_checkpoint(block, frame, snapshot)
        self.checkpoint_exports += 1
        return cp

    def _sealed_block_at_round(self, at_round: int):
        """Earliest SEALED block with round_received >= at_round, or
        None. Blocks are round-monotonic in index, so binary search for
        the boundary, then walk forward past any not-yet-sealed blocks
        (signatures accumulate for a round or two after commit)."""
        store = self.core.hg.store
        last = self.core.get_last_block_index()
        if last < 0:
            return None
        lo, hi = 0, last
        while lo < hi:
            mid = (lo + hi) // 2
            try:
                if store.get_block(mid).round_received() < at_round:
                    lo = mid + 1
                else:
                    hi = mid
            except Exception:  # noqa: BLE001 — evicted: search higher
                lo = mid + 1
        for index in range(lo, last + 1):
            block = self.get_sealed_block(index)
            if block is not None and block.round_received() >= at_round:
                return block
        return None

    def get_stats_snapshot(self) -> Dict[str, object]:
        """One TYPED stats snapshot (numbers stay numbers) — the single
        source for ``get_stats`` (string view, the reference contract),
        the mobile JSON surface, and the /stats endpoint. The same
        underlying counters back the registry instruments served at
        /metrics (docs/observability.md; compat contract: docs/parity.md
        #27)."""
        stats: Dict[str, object] = {
            "last_consensus_round": self.get_last_consensus_round_index(),
            "last_block_index": self.get_last_block_index(),
            "consensus_events": self.core.get_consensus_events_count(),
            "undetermined_events": len(self.core.get_undetermined_events()),
            "transactions": self.core.get_consensus_transactions_count(),
            "transaction_pool": self.core.mempool.pending_count,
            "num_peers": len(self.core.peer_selector.get_peers()),
            "last_peer_change": self.core.last_peer_change_round,
            "id": self.get_id(),
            "state": str(self.get_state()),
            "moniker": self.core.validator.moniker,
        }
        # Batched-ingest fast-path counters (ISSUE-1 pipeline): one batch
        # verify per sync on the happy path, fallback singles pinpoint
        # offenders, lock_wait measures residual core-lock contention,
        # and the serialization-cache counters are process-wide (shared
        # by co-located nodes).
        from ..crypto.batch import VERIFY_CACHE
        from ..crypto.canonical import NORM_CACHE
        from ..hashgraph.event import WIRE_CACHE

        stats.update(
            {
                "ingest_syncs": self.core.ingest_syncs,
                "ingest_batch_verifies": self.core.ingest_batch_verifies,
                "ingest_batch_size_max": self.core.ingest_batch_size_max,
                "ingest_fallback_singles": self.core.ingest_fallback_singles,
                "lock_wait_ms_total": round(
                    self.core_lock.wait_ms_total(), 1
                ),
                "lock_acquisitions": self.core_lock.acquisitions,
                # BABBLE_LOCKCHECK acquisition-order recorder (process-
                # wide; empty list / 0 while the recorder is disarmed).
                # Any inversion is a latent deadlock — the lockcheck'd
                # chaos and sim CI legs assert this stays 0
                # (docs/static_analysis.md §Lock model).
                "lock_order_edges": lockcheck.RECORDER.edge_list(),
                "lock_order_inversions": len(
                    lockcheck.RECORDER.inversions()
                ),
                "wire_cache_hits": WIRE_CACHE.hits,
                "wire_cache_misses": WIRE_CACHE.misses,
                "norm_cache_hits": NORM_CACHE.hits,
                "norm_cache_misses": NORM_CACHE.misses,
                "verify_cache_hits": VERIFY_CACHE.hits,
                "verify_cache_misses": VERIFY_CACHE.misses,
            }
        )
        # Mempool surface (docs/mempool.md): admission verdict counters,
        # pending gauges, eviction/requeue totals.
        stats.update(
            {
                f"mempool_{k}": v
                for k, v in self.core.mempool.stats().items()
            }
        )
        # Robustness surface: handler crash counters per RPC type, the
        # gossip-side transport failure counter, the peer selector's
        # health/backoff view of the network, and the sentry's
        # misbehavior/quarantine ledger.
        stats.update(
            {f"rpc_errors_{k}": v for k, v in self.rpc_errors.items()}
        )
        stats["gossip_transport_errors"] = self.gossip_transport_errors
        # Causal-tracing / flight-recorder surface
        # (docs/observability.md §Causal tracing)
        stats["trace_ctx_rpcs"] = self.trace_ctx_rpcs
        prov = self.telemetry.provenance.stats()
        stats["trace_sampled_txs"] = prov["sampled_total"]
        stats["trace_provenance_entries"] = prov["entries"]
        stats["trace_provenance_evictions"] = prov["evictions"]
        stats["watchdog_trips"] = self.watchdog.trips
        stats["flight_dumps"] = self.watchdog.dumps
        # Light-client gateway surface (docs/clients.md): subscription
        # hub occupancy (zeros while --client-listen is off) + the
        # proof-serving counters.
        hub = self.client_hub.stats() if self.client_hub is not None else {}
        stats["client_subscribers"] = hub.get("subscribers", 0)
        stats["client_sub_queue_frames_max"] = hub.get("queue_frames_max", 0)
        stats["client_pushed_blocks"] = hub.get("pushed_blocks", 0)
        stats["client_shed_subscribers"] = hub.get("shed", 0)
        stats["client_proofs_served"] = self.proofs_served
        stats["client_proof_misses"] = self.proof_misses
        stats["client_txindex_entries"] = len(self.txindex)
        stats["client_checkpoint_exports"] = self.checkpoint_exports
        # Lifecycle tier surface (docs/lifecycle.md): retention floor,
        # prune counters, and the store's retained-size view — the
        # lifecycle_* instruments and healthview columns read these.
        hg_floor = self.core.hg.prune_floor
        lcr = stats["last_consensus_round"]
        stats["lifecycle_prune_floor"] = -1 if hg_floor is None else hg_floor
        stats["lifecycle_prune_lag_rounds"] = max(
            0, int(lcr) - max(hg_floor or 0, 0)
        )
        stats["lifecycle_prunes"] = 0 if self.pruner is None else self.pruner.prunes
        stats["lifecycle_pruned_events"] = (
            0 if self.pruner is None else self.pruner.events_pruned
        )
        stats["lifecycle_behind_retention"] = self.behind_retention_rejections
        sz = self._store_size_stats()
        stats["lifecycle_events_retained"] = sz.get("events", 0)
        stats["lifecycle_rounds_retained"] = sz.get("rounds", 0)
        stats["lifecycle_store_bytes"] = sz.get("store_bytes", 0)
        stats.update(self.core.peer_selector.stats())
        stats["sync_limit_truncations"] = self.sync_limit_truncations
        stats["sync_diff_truncations"] = self.sync_diff_truncations
        # Adaptive gossip scheduler surface (docs/gossip.md §Adaptive
        # scheduling): the controller's published plan + change count,
        # coalesced self-event minting, and the per-peer lag extremes
        # feeding the law. With adaptation off the fixed two-speed law
        # is reported in the same keys so dashboards need no branches.
        if self.adaptive is not None:
            stats.update(self.adaptive.stats())
        else:
            # gossip_plan IS the fixed two-speed law (and is
            # side-effect-free) when the controller is off
            interval, fanout = self.gossip_plan()
            stats.update({
                "adaptive_interval_ms": round(1e3 * interval, 3),
                "adaptive_fanout": fanout,
                "adaptive_soft_depth": self.conf.gossip_pipeline_depth,
                "adaptive_ticks": 0,
                "adaptive_adjustments": 0,
            })
        peer_behind, self_behind = self._lag_extremes()
        stats["gossip_peer_behind_max"] = peer_behind
        stats["gossip_self_behind_max"] = self_behind
        stats["selfevent_coalesced"] = self.core.selfevent_coalesced
        # Async gossip engine surface (docs/gossip.md): inbound-sync
        # pipeline occupancy + the process-wide binary codec tallies.
        if self.pipeline is not None:
            stats.update(self.pipeline.stats())
        else:
            stats.update({
                "gossip_inflight_syncs": 0,
                "gossip_inflight_syncs_peak": 0,
                "gossip_pipelined_syncs": 0,
                "gossip_pull_pipelined_syncs": 0,
                "gossip_backpressure_stalls": 0,
                "gossip_pipeline_queue_depth": 0,
                "gossip_pipeline_soft_depth": self.conf.gossip_pipeline_depth,
            })
        from ..net.codec import CODEC_STATS

        stats.update({
            f"codec_{k}": v for k, v in CODEC_STATS.snapshot().items()
        })
        stats.update(self.core.sentry.stats())
        # Commit-latency percentiles from the registry histogram — the
        # north-star p50/p90/p99 (ms), None until the first local commit.
        clat = self.telemetry.commit_latency_ms()
        stats["commit_latency_samples"] = clat["count"]
        stats["commit_latency_p50_ms"] = clat["p50_ms"]
        stats["commit_latency_p90_ms"] = clat["p90_ms"]
        stats["commit_latency_p99_ms"] = clat["p99_ms"]
        accel = self.core.hg.accel
        if accel is not None:
            stats.update(accel.stats())
        else:
            stats["consensus_engine"] = "oracle"
        return stats

    def get_stats(self) -> Dict[str, str]:
        """reference: node.go:277-294 — the reference's stringly map,
        derived at the edge from the typed snapshot."""
        return {k: str(v) for k, v in self.get_stats_snapshot().items()}

    def _store_size_stats(self) -> Dict[str, int]:
        """Memoized store.size_stats() (≤1 read/second — the persistent
        store's implementation runs COUNT(*) queries)."""
        now = self.clock.monotonic()
        memo = self._size_stats_memo
        if memo["v"] is None or now - float(memo["t"]) >= 1.0:
            size_stats = getattr(self.core.hg.store, "size_stats", None)
            memo["v"] = size_stats() if size_stats is not None else {}
            memo["t"] = now
        return memo["v"]

    # -- background ---------------------------------------------------------

    def _do_background_work(self) -> None:
        """Drain transport RPCs and submitted transactions
        (reference: node.go:341-361)."""
        net_q = self.trans.consumer()
        while not self.shutdown_event.is_set():
            handled = False
            try:
                rpc = net_q.get(timeout=0.01)
                handled = True
                started = self.go_func(
                    lambda r=rpc: (self._process_rpc(r), self._reset_timer())
                )
                if not started:
                    # routine pool exhausted: answer instead of dropping
                    # silently, so the caller fails fast rather than
                    # burning its full RPC timeout (backpressure surface)
                    rpc.respond(None, "node busy (routine pool exhausted)")
            except queue.Empty:
                pass
            # Batch-drain the submit queue, BOUNDED per pass: the old
            # one-get_nowait-per-transaction shape admitted one tx per
            # loop iteration under load, while an unbounded drain would
            # starve the transport consumer above. Up to conf.submit_batch
            # transactions go through mempool admission per pass.
            try:
                for _ in range(max(1, self.conf.submit_batch)):
                    tx = self.submit_q.get_nowait()
                    handled = True
                    self._add_transaction(tx)
            except queue.Empty:
                pass
            if handled:
                self._reset_timer()

    def _reset_timer(self) -> None:
        """reference: node.go:365-379 — interval now chosen by
        :meth:`gossip_plan` (adaptive controller, or the reference's
        fixed two-speed law when adaptation is off).

        The signals read are snapshot reads of plain attributes (pool
        lengths, pending counters) — taking the core lock for them only
        added contention on the insert pipeline; a momentarily stale
        choice is harmless (the next tick re-reads)."""
        if not self.control_timer.is_set:
            interval, _ = self.gossip_plan()
            self.control_timer.reset(interval)

    def gossip_plan(self) -> tuple:
        """(interval_s, fanout) for the next gossip tick. With the
        adaptive controller on, one signal snapshot is folded into the
        control law (EWMA + hysteresis, node/adaptive.py) and the
        pipeline's soft depth cap is re-published; with it off, the
        reference's fixed law: heartbeat when busy, slow heartbeat when
        idle, one partner per tick."""
        busy = self.core.busy()
        if self.adaptive is None:
            interval = (
                self.conf.heartbeat_timeout
                if busy
                else self.conf.slow_heartbeat_timeout
            )
            return interval, 1
        from .adaptive import GossipSignals

        peer_behind, self_behind = self._lag_extremes()
        sig = GossipSignals(
            busy=busy,
            mempool_pending=self.core.mempool.pending_count,
            inflight=self.pipeline.inflight if self.pipeline else 0,
            queue_depth=(
                self.pipeline.queue_depth() if self.pipeline else 0
            ),
            peer_behind=peer_behind,
            self_behind=self_behind,
            rounds_inflight=self._rounds_carryover,
            rounds_cap=self._gossip_slot_cap,
        )
        with self._plan_lock:
            now = self.clock.monotonic()
            if now - self._last_plan_t >= self.adaptive.fast_s:
                plan = self.adaptive.update(sig)
                self._last_plan_t = now
            else:
                # mid-interval caller (an RPC-handler _reset_timer):
                # reuse the published plan, don't re-fold the EWMAs
                plan = self.adaptive.current()
            self._fanout = plan.fanout
        if self.pipeline is not None:
            self.pipeline.set_soft_depth(plan.soft_depth)
        return plan.interval, plan.fanout

    # -- per-peer lag (adaptive signals) ------------------------------------

    def _note_peer_known(
        self, peer_id: int, ours: Dict[int, int], theirs: Dict[int, int]
    ) -> None:
        """Fold one exchanged known-map pair into the per-peer lag view:
        total events the peer is missing that we hold (``peer_behind``)
        and vice versa (``self_behind``). Called from both gossip legs,
        so every contact refreshes its partner's entry."""
        peer_behind = 0
        self_behind = 0
        for cid, our_idx in ours.items():
            their_idx = theirs.get(cid, -1)
            if our_idx > their_idx:
                peer_behind += our_idx - their_idx
        for cid, their_idx in theirs.items():
            if their_idx > ours.get(cid, -1):
                self_behind += their_idx - ours.get(cid, -1)
        with self._lag_lock:
            self._peer_behind[peer_id] = peer_behind
            self._self_behind[peer_id] = self_behind

    def _lag_extremes(self) -> tuple:
        """(max events any peer trails us by, max events we trail any
        peer by) over the last contact with each CURRENT peer — entries
        for since-removed peers are ignored (and dropped), so a departed
        laggard can't pin the fan-out open forever."""
        live = {p.id for p in self.core.peer_selector.get_peers().peers}
        with self._lag_lock:
            for d in (self._peer_behind, self._self_behind):
                for pid in [k for k in d if k not in live]:
                    del d[pid]
            peer_behind = max(self._peer_behind.values(), default=0)
            self_behind = max(self._self_behind.values(), default=0)
        return peer_behind, self_behind

    def _check_suspend(self) -> None:
        """Auto-suspend on runaway undetermined events or eviction
        (reference: node.go:384-408)."""
        new_undetermined = (
            len(self.core.get_undetermined_events())
            - self.initial_undetermined_events
        )
        too_many = new_undetermined > self.conf.suspend_limit * len(
            self.core.validators
        )
        evicted = (
            self.core.hg.last_consensus_round is not None
            and self.core.removed_round > 0
            and self.core.removed_round > self.core.accepted_round
            and self.core.hg.last_consensus_round >= self.core.removed_round
        )
        if too_many or evicted:
            self.suspend()

    # -- babbling -----------------------------------------------------------

    def _babble(self, gossip: bool) -> None:
        """Gossip on each timer tick (reference: node.go:416-443).

        The wait is EVENT-driven: the loop blocks on the tick event
        itself (suspend/shutdown poke it, so exits stay prompt) instead
        of the old 100 ms polling wait, which both burned a core and
        floored the achievable gossip interval at the poll quantum —
        the adaptive controller's fast rail is the heartbeat itself,
        not heartbeat-rounded-up-to-100ms. The long timeout below is a
        lost-wakeup guard only, never the cadence."""
        self.logger.info("BABBLING")
        self.suspend_event.clear()
        while True:
            if self.shutdown_event.is_set() or self.suspend_event.is_set():
                return
            if self.get_state() != State.BABBLING:
                return
            if self.control_timer.tick.wait(timeout=5.0):
                if (
                    self.shutdown_event.is_set()
                    or self.suspend_event.is_set()
                ):
                    self.control_timer.tick.clear()
                    return
                self.control_timer.tick.clear()
                # rounds still running from the previous tick = the
                # cadence is overrunning the host (adaptive congestion)
                self._rounds_carryover = self._gossip_rounds_inflight
                if gossip:
                    peers = self.core.peer_selector.next_many(self._fanout)
                    if peers:
                        for peer in peers:
                            if not self._gossip_slots.acquire(blocking=False):
                                break  # fan the rest next tick
                            started = self.go_func(
                                lambda p=peer: self._gossip_with_slot(p)
                            )
                            if not started:
                                self._gossip_slots.release()
                                break
                    else:
                        self._monologue()
                self._reset_timer()
                self._check_suspend()

    def _gossip_with_slot(self, peer: Peer) -> None:
        with self._rounds_lock:
            self._gossip_rounds_inflight += 1
        try:
            self._gossip(peer)
        finally:
            with self._rounds_lock:
                self._gossip_rounds_inflight -= 1
            self._gossip_slots.release()

    def _monologue(self) -> None:
        """Record events even when alone (reference: node.go:447-463)."""
        with self.core_lock:
            if self.core.busy():
                self.core.add_self_event("")
                self.core.drain_hot_mempool()
                self.core.hg.flush_consensus()
                self.core.process_sig_pool()
        self._maybe_prune()

    def _gossip(self, peer: Peer) -> None:
        """Pull-push gossip round (reference: node.go:466-501).

        The whole round runs under one sync trace: stages timed here and
        deep in the core/hashgraph pipeline attach to it through the
        tracer's thread-local, and the finished span lands in the
        /telemetry recent-syncs ring."""
        connected = False
        transport_failure = False
        trace = self.telemetry.start_sync_trace(peer.id)
        try:
            other_known = self._pull(peer)
            self._push(peer, other_known)
            connected = True
            self.last_gossip_ok = self.clock.monotonic()
            self._log_stats()
        except TransportError as err:
            transport_failure = True
            self.gossip_transport_errors += 1
            self.logger.debug("gossip transport error: %s", err)
        except Exception as err:
            # Classified ingest rejections (typed hashgraph errors) feed
            # the sentry: the pull leg's events came from this peer, so
            # hostile payloads score it (forks score their creator).
            cause = self.core.sentry.observe_rejection(err, peer.id)
            if cause is not None:
                self.logger.warning(
                    "gossip rejection from %d (%s): %s", peer.id, cause, err
                )
            else:
                self.logger.warning("gossip error: %s", err)
        finally:
            trace.finish()
            # only NETWORK failures decay the peer's health/backoff; a
            # local error (the generic branch) isn't the peer's fault
            self.core.peer_selector.update_last(
                peer.id, connected, penalize=transport_failure
            )
        self._maybe_prune()

    def _maybe_prune(self) -> None:
        """Checkpoint-prune hook (docs/lifecycle.md), run from the
        gossip/monologue tails — NEVER from the commit listener, where
        compaction would mutate the store mid process_decided_rounds.
        The due() pre-check is lock-free; the prune itself re-evaluates
        under the core lock."""
        if self.pruner is None or not self.pruner.due(self.core):
            return
        with self.core_lock:
            stats = self.pruner.prune(self.core)
        if stats is not None:
            self.logger.info(
                "checkpoint-prune: floor=%d events=%d rounds=%d",
                stats["floor"],
                stats["events_pruned"],
                stats["rounds_pruned"],
            )

    def _pull(self, peer: Peer) -> Dict[int, int]:
        """SyncRequest leg (reference: node.go:504-538).

        With the staged pipeline on, the pulled events go through the
        SAME decode→batch-verify→bounded-queue→single-inserter staging
        as inbound eager syncs (node/pipeline.py): stage 1 runs here in
        the gossip thread (lock-free), the insert tail drains on the
        inserter — so a slow insert never blocks this round's push leg
        or the next pull round-trip. Inline fallback (pipeline off, sim
        clock, or stopped) keeps the pre-pipeline shape."""
        with self.core_lock:
            known = self.core.known_events()
        t0 = self.clock.monotonic()
        resp = self._request_sync(peer.net_addr, known, self.conf.sync_limit)
        # response arrival: the pulled events' "recv" stamp for per-hop
        # trace attribution (no wire ctx on a pull — the latency is OUR
        # request_sync round-trip, not a remote push)
        recv = self.clock.time() if self.telemetry.enabled else None
        dt = self.clock.monotonic() - t0
        self.timers.record("request_sync", dt)
        self.telemetry.observe_stage("request_sync", dt)
        self._note_peer_known(peer.id, known, resp.known)
        if len(resp.events) > self.conf.sync_limit:
            # We asked for at most sync_limit events; a bigger response
            # means the peer ignored the negotiated cap.
            resp.events = resp.events[: self.conf.sync_limit]
            self.sync_limit_truncations += 1
            self.core.sentry.record(peer.id, "oversized_sync")
        t0 = self.clock.monotonic()
        hop = {"from": peer.id, "recv": recv}
        if (
            self.pipeline is not None
            and resp.events
            and self.pipeline.submit_pull(peer.id, resp.events, hop)
        ):
            self.timers.record("sync", self.clock.monotonic() - t0)
            return resp.known
        # Lock-free ingest stage: decode + hash + one batch signature
        # verification happen BEFORE the core lock; the lock then only
        # covers the ordered insert + DivideRounds sweep.
        prepared = self.core.prepare_sync(resp.events)
        with self.core_lock:
            self._sync(peer.id, resp.events, prepared, hop=hop)
        self.timers.record("sync", self.clock.monotonic() - t0)
        return resp.known

    def _push(self, peer: Peer, known_events: Dict[int, int]) -> None:
        """EagerSyncRequest leg (reference: node.go:541-587)."""
        t0 = self.clock.monotonic()
        with self.core_lock:
            diff = self.core.event_diff(known_events)
        dt = self.clock.monotonic() - t0
        self.timers.record("diff", dt)
        self.telemetry.observe_stage("diff", dt)
        if not diff:
            return
        if len(diff) > self.conf.sync_limit:
            # Sender-side truncation is no longer silent: the counter is
            # the receiving side's sync_limit_truncations twin, so a
            # peer chronically more than one sync_limit behind us is
            # visible in get_stats//metrics instead of just staying lag.
            diff = diff[: self.conf.sync_limit]
            self.sync_diff_truncations += 1
        wire = self.core.to_wire(diff)
        t0 = self.clock.monotonic()
        self._request_eager_sync(peer.net_addr, wire)
        dt = self.clock.monotonic() - t0
        self.timers.record("eager_sync", dt)
        self.telemetry.observe_stage("eager_sync", dt)

    def _sync(
        self,
        from_id: int,
        events: List[WireEvent],
        prepared: Optional[PreparedSync] = None,
        hop: Optional[dict] = None,
    ) -> None:
        """Insert events + process the sig pool; callers hold core_lock
        and SHOULD pass the prepare_sync output computed outside it
        (reference: node.go:591-615). ``hop`` is the carrying sync's
        causal-trace info for per-transaction provenance (Core.sync)."""
        try:
            self.core.sync(from_id, events, prepared, hop)
        except Exception as err:
            if not is_normal_self_parent_error(err):
                raise
        finally:
            # Always drain the sig pool: Core.sync defers a ForkError
            # until after the batch's inserts complete, so the block
            # signatures those events carried must not sit unprocessed
            # behind the re-raise.
            t0 = self.clock.monotonic()
            self.core.process_sig_pool()
            dt = self.clock.monotonic() - t0
            self.timers.record("process_sig_pool", dt)
            self.telemetry.observe_stage("process_sig_pool", dt)

    # -- catching up --------------------------------------------------------

    def _fast_forward(self) -> None:
        """reference: node.go:622-666."""
        self.logger.info("CATCHING-UP")
        self.wait_routines(timeout=2.0)

        resp = self._get_best_fast_forward_response()
        if resp is None:
            self._transition(State.BABBLING)
            return

        try:
            self.proxy.restore(resp.snapshot)
            with self.core_lock:
                self.core.fast_forward(resp.block, resp.frame)
            self.core.process_accepted_internal_transactions(
                resp.block.round_received(),
                resp.block.internal_transaction_receipts(),
            )
        except Exception as err:
            self.logger.error("fast-forward failed: %s", err)
            return

        self._transition(State.BABBLING)

    def _get_best_fast_forward_response(self) -> Optional[FastForwardResponse]:
        """Poll all peers, keep the highest block (reference: node.go:670-701).

        A catching-up node on a flaky network must not give up because ONE
        poll pass hit transport errors: passes retry with exponential
        backoff (jittered) until conf.fast_forward_deadline. A pass where
        every peer ANSWERED (a response or a RemoteError — e.g. "no
        anchor block" in a young cluster) is conclusive — no retry — as
        is a cluster with no other peers. Only connectivity failures,
        which retrying can heal, re-poll."""
        from ..common.backoff import jittered_backoff

        deadline = self.clock.monotonic() + self.conf.fast_forward_deadline
        attempt = 0
        while True:
            best: Optional[FastForwardResponse] = None
            max_block = 0
            transport_errors = 0
            for p in self.core.peer_selector.get_peers().peers:
                if p.id == self.get_id():
                    continue
                try:
                    resp = self._request_fast_forward(p.net_addr)
                except TransportError as err:
                    if not isinstance(err, RemoteError):
                        transport_errors += 1
                    self.logger.debug(
                        "requestFastForward(%s): %s", p.net_addr, err
                    )
                    continue
                if resp.block is not None and resp.block.index() > max_block:
                    best = resp
                    max_block = resp.block.index()
            if best is not None or transport_errors == 0:
                return best
            attempt += 1
            delay = jittered_backoff(attempt, 0.1, 1.0, rng=self._backoff_rng)
            if (
                self.clock.monotonic() + delay > deadline
                or self.shutdown_event.is_set()
            ):
                return None
            self.clock.sleep(delay)

    # -- joining ------------------------------------------------------------

    def _join(self) -> None:
        """reference: node.go:709-751."""
        if self.conf.maintenance_mode:
            return
        self.logger.info("JOINING")
        peer = self.core.peer_selector.next()
        if peer is None:
            self.clock.sleep(0.2)
            return
        try:
            resp = self._request_join(peer.net_addr)
        except TransportError as err:
            self.logger.warning("cannot join via %s: %s", peer.net_addr, err)
            # feed the selector so the next attempt prefers another peer,
            # and back off exponentially (jittered, capped) — the run loop
            # re-enters _join, so the sleep here IS the retry cadence
            from ..common.backoff import backoff_sleep

            self.core.peer_selector.update_last(peer.id, False)
            self._join_failures += 1
            backoff_sleep(
                self._join_failures, 0.2, self.conf.join_backoff_cap,
                rng=self._backoff_rng, sleep=self.clock.sleep,
            )
            return

        self._join_failures = 0
        self.core.peer_selector.update_last(peer.id, True)
        if resp.accepted:
            self.core.accepted_round = resp.accepted_round
            self.core.removed_round = -1
            self._set_babbling_or_catching_up_state()
        else:
            self.logger.info("join request rejected")
            self.shutdown()

    # -- client-side RPCs (reference: node_rpc.go:15-74) --------------------

    def _request_sync(
        self, target: str, known: Dict[int, int], sync_limit: int
    ) -> SyncResponse:
        return self.trans.sync(
            target,
            SyncRequest(
                self.get_id(), known, sync_limit,
                trace=self.telemetry.wire_ctx(self.get_id()),
            ),
        )

    def _request_eager_sync(
        self, target: str, events: List[WireEvent]
    ) -> EagerSyncResponse:
        return self.trans.eager_sync(
            target,
            EagerSyncRequest(
                self.get_id(), events,
                trace=self.telemetry.wire_ctx(self.get_id()),
            ),
        )

    def _request_fast_forward(self, target: str) -> FastForwardResponse:
        return self.trans.fast_forward(
            target,
            FastForwardRequest(
                self.get_id(), trace=self.telemetry.wire_ctx(self.get_id())
            ),
        )

    def _request_join(self, target: str) -> JoinResponse:
        join_tx = InternalTransaction.join(
            Peer(
                net_addr=self.trans.advertise_addr(),
                pub_key_hex=self.core.validator.public_key_hex(),
                moniker=self.core.validator.moniker,
            )
        )
        join_tx.sign(self.core.validator.key)
        return self.trans.join(target, JoinRequest(join_tx))

    # -- server-side RPCs (reference: node_rpc.go:76-315) -------------------

    def _process_rpc(self, rpc: RPC) -> None:
        """Gate on state, dispatch by command type
        (reference: node_rpc.go:76-104)."""
        state = self.get_state()
        is_sync = isinstance(rpc.command, SyncRequest)
        if not (
            state == State.BABBLING or (state == State.SUSPENDED and is_sync)
        ):
            rpc.respond(None, f"not in Babbling state ({state})")
            return

        cmd = rpc.command
        if getattr(cmd, "trace", None) is not None:
            # wire trace context present (absent from old peers — both
            # directions interoperate, docs/observability.md)
            self.trace_ctx_rpcs += 1
        # Quarantined peers get no sync service: their pushes are the
        # attack surface and their pulls only help them keep up. Join and
        # fast-forward stay open (different identity/recovery paths).
        if isinstance(cmd, (SyncRequest, EagerSyncRequest)):
            if self.core.sentry.is_quarantined(cmd.from_id):
                self.core.sentry.note_refused()
                rpc.respond(None, f"peer {cmd.from_id} is quarantined")
                return
        if isinstance(cmd, SyncRequest):
            self._process_sync_request(rpc, cmd)
        elif isinstance(cmd, EagerSyncRequest):
            self._process_eager_sync_request(rpc, cmd)
        elif isinstance(cmd, FastForwardRequest):
            self._process_fast_forward_request(rpc, cmd)
        elif isinstance(cmd, JoinRequest):
            self._process_join_request(rpc, cmd)
        else:
            rpc.respond(None, "unexpected command")

    def _process_sync_request(self, rpc: RPC, cmd: SyncRequest) -> None:
        """reference: node_rpc.go:106-172."""
        self.sync_requests += 1
        resp = SyncResponse(from_id=self.get_id())
        err: Optional[str] = None
        try:
            with self.core_lock:
                diff = self.core.event_diff(cmd.known)
            # clamp: a hostile negative sync_limit must not turn
            # diff[:limit] into serve-almost-everything
            limit = min(max(0, cmd.sync_limit), self.conf.sync_limit)
            if len(diff) > limit:
                diff = diff[:limit]
            resp.events = self.core.to_wire(diff)
            with self.core_lock:
                resp.known = self.core.known_events()
            # the requester told us what it knows: refresh its lag entry
            # (adaptive spread signal) without waiting for our own pull
            self._note_peer_known(cmd.from_id, resp.known, cmd.known)
        except Exception as e:
            self.sync_errors += 1
            self.rpc_errors["sync"] += 1
            self.logger.debug("sync handler error: %s", e, exc_info=True)
            err = str(e)
        rpc.respond(resp, err)

    def _process_eager_sync_request(self, rpc: RPC, cmd: EagerSyncRequest) -> None:
        """reference: node_rpc.go:180-203."""
        if len(cmd.events) > self.conf.sync_limit:
            # Receiving-side cap: the requester-side truncation
            # (node.py _push) is a courtesy honest peers extend; a
            # hostile pusher ignores it, so the cap is enforced here
            # too. Scoring only kicks in past 2x our limit: eager-push
            # has no negotiation leg, so an honest peer configured with
            # a larger --sync-limit would otherwise be punished for a
            # pure config mismatch (the pull leg negotiates explicitly,
            # so there any overshoot is scored).
            egregious = len(cmd.events) > 2 * self.conf.sync_limit
            cmd.events = cmd.events[: self.conf.sync_limit]
            self.sync_limit_truncations += 1
            if egregious:
                self.core.sentry.record(cmd.from_id, "oversized_sync")
        hop = None
        if self.telemetry.enabled:
            hop = {
                "from": cmd.from_id,
                "ctx": parse_ctx(cmd.trace),
                # transport arrival when stamped; else handler entry
                "recv": (
                    rpc.recv_ts if rpc.recv_ts is not None
                    else self.clock.time()
                ),
            }
        # Pipelined path (node/pipeline.py): decode+batch-verify run in
        # THIS thread (stage 1, lock-free, overlapped across concurrent
        # inbound syncs), the insert tail drains through the serialized
        # inserter, and the response fires after the insert lands.
        if self.pipeline is not None and self.pipeline.submit(rpc, cmd, hop):
            return
        # Inline fallback (pipeline disabled or stopped): the
        # pre-pipeline shape — same lock-shrink, same error surface.
        try:
            prepared = self.core.prepare_sync(cmd.events)
        except Exception as e:
            self._fail_eager_sync(rpc, cmd, e)
            return
        self._finish_eager_sync(rpc, cmd, prepared, hop)

    def _fail_eager_sync(self, rpc: RPC, cmd: EagerSyncRequest,
                         e: Exception) -> None:
        """Answer an eager sync whose prepare stage raised, preserving
        the pre-pipeline error attribution: classified (peer-fault)
        rejections score the sender through the sentry; only genuine
        handler crashes count toward rpc_errors."""
        cause = self.core.sentry.observe_rejection(e, cmd.from_id)
        if cause is None:
            self.rpc_errors["eager_sync"] += 1
        self.logger.debug("eager-sync prepare error: %s", e, exc_info=True)
        rpc.respond(EagerSyncResponse(self.get_id(), False), str(e))

    def _finish_eager_sync(self, rpc: RPC, cmd: EagerSyncRequest,
                           prepared, hop: Optional[dict]) -> None:
        """Insert tail of one inbound eager sync + the response. Called
        by the pipeline's inserter thread (or inline when the pipeline
        is off); ``prepared`` is the lock-free stage's output for
        ``cmd.events``."""
        success = True
        err: Optional[str] = None
        try:
            with self.core_lock:
                self._sync(cmd.from_id, cmd.events, prepared, hop)
        except Exception as e:
            success = False
            cause = self.core.sentry.observe_rejection(e, cmd.from_id)
            if cause is None:
                # not the peer's fault — a genuine handler crash
                self.rpc_errors["eager_sync"] += 1
            self.logger.debug(
                "eager-sync handler error: %s", e, exc_info=True
            )
            err = str(e)
        rpc.respond(EagerSyncResponse(self.get_id(), success), err)

    def _fail_pulled_sync(self, from_id: int, e: Exception) -> None:
        """Insert-tail failure of a pulled batch on the inserter thread
        (stage-1 failures propagate out of submit_pull to _gossip's own
        handler instead) — same attribution as the inline pull leg:
        classified hashgraph rejections score the serving peer through
        the sentry; anything else is a local error and only gets
        logged."""
        cause = self.core.sentry.observe_rejection(e, from_id)
        if cause is not None:
            self.logger.warning(
                "gossip rejection from %d (%s): %s", from_id, cause, e
            )
        else:
            self.logger.warning("pulled-sync error: %s", e)

    def _finish_pulled_sync(self, from_id: int, events: List[WireEvent],
                            prepared, hop: Optional[dict]) -> None:
        """Insert tail of one pulled batch. Called by the pipeline's
        inserter thread (or inline on the queue-full backpressure path);
        ``prepared`` is the lock-free stage's output for ``events``.
        There is no RPC to answer. A rejection here lands AFTER the
        gossip round already recorded the contact (the round's success
        is the wire exchange; the staged insert is deliberately off its
        critical path), so the feedback channel for a peer serving bad
        payloads is the sentry — repeated classified rejections
        quarantine it, which the selector hard-excludes — matching the
        inline path's real defense (insert rejections never decayed
        selector health there either; only transport failures do)."""
        try:
            with self.core_lock:
                self._sync(from_id, events, prepared, hop)
        except Exception as e:
            self._fail_pulled_sync(from_id, e)

    def _process_fast_forward_request(
        self, rpc: RPC, cmd: FastForwardRequest
    ) -> None:
        """reference: node_rpc.go:205-247."""
        resp = FastForwardResponse(from_id=self.get_id())
        err: Optional[str] = None
        try:
            with self.core_lock:
                block, frame = self.core.get_anchor_block_with_frame()
            resp.block = block
            resp.frame = frame
            resp.snapshot = self.proxy.get_snapshot(block.index())
        except Exception as e:
            self.rpc_errors["fast_forward"] += 1
            self.logger.debug(
                "fast-forward handler error: %s", e, exc_info=True
            )
            err = str(e)
        rpc.respond(resp, err)

    def _process_join_request(self, rpc: RPC, cmd: JoinRequest) -> None:
        """reference: node_rpc.go:249-315."""
        err: Optional[str] = None
        accepted = False
        accepted_round = 0
        peers: List[Peer] = []

        itx = cmd.internal_transaction
        if not itx.verify():
            err = "unable to verify signature on join request"
        elif itx.body.peer.pub_key_hex in self.core.peers.by_pub_key:
            accepted = True
            lcr = self.core.get_last_consensus_round_index()
            if lcr is not None:
                accepted_round = lcr
            peers = self.core.peers.peers
        else:
            with self.core_lock:
                promise = self.core.add_internal_transaction(itx)
            try:
                presp = promise.wait(timeout=self.conf.join_timeout)
                accepted = presp.accepted
                accepted_round = presp.accepted_round
                peers = presp.peers
            except queue.Empty:
                err = "timeout waiting for join request to reach consensus"
        if err is not None:
            self.rpc_errors["join"] += 1
            self.logger.debug("join handler error: %s", err)
        rpc.respond(
            JoinResponse(self.get_id(), accepted, accepted_round, peers), err
        )

    # -- utils --------------------------------------------------------------

    def _transition(self, state: State) -> None:
        """reference: node.go:758-765."""
        self.set_state(state)
        try:
            self.proxy.on_state_changed(state)
        except Exception as err:
            self.logger.error("OnStateChanged: %s", err)

    def _set_babbling_or_catching_up_state(self) -> None:
        """reference: node.go:768-780."""
        if self.conf.enable_fast_sync:
            self._transition(State.CATCHING_UP)
        else:
            self.core.set_head_and_seq()
            self._transition(State.BABBLING)

    def _add_transaction(self, tx: bytes) -> str:
        """reference: node.go:784-789 — but admission happens under the
        mempool's OWN lock, not the core lock: a submit storm contends
        with other submits, never with the insert/consensus pipeline."""
        return self._admit_transaction(tx)

    def _admit_transaction(self, tx: bytes) -> str:
        """Mempool admission; returns the verdict (proxy submit handler)."""
        return self.core.mempool.submit(tx)

    def get_metrics_text(self) -> str:
        """/metrics service payload: Prometheus text exposition of the
        node registry + the process-global registry."""
        return self.telemetry.render_metrics()

    def get_telemetry(self) -> Dict[str, object]:
        """/telemetry service payload: every instrument as JSON
        (histograms with computed p50/p90/p99) + recent sync traces."""
        return self.telemetry.telemetry_view()

    def get_mempool(self) -> Dict[str, object]:
        """/mempool service payload: knobs + live counters."""
        return {
            "config": self.core.mempool.config(),
            "stats": self.core.mempool.stats(),
        }

    def get_trace(self, txid: str) -> Optional[Dict[str, object]]:
        """/trace/<txid> service payload: THIS node's provenance record
        for one transaction (None → 404; obs/traceview.py merges several
        nodes' answers into the cross-node timeline)."""
        rec = self.telemetry.provenance.get(txid)
        if rec is None:
            return None
        rec["node"] = self.get_id()
        rec["moniker"] = self.core.validator.moniker
        return rec

    def get_traces(self, limit: int = 256) -> Dict[str, object]:
        """/traces service payload: bulk provenance export (newest-last,
        bounded) plus the table's own stats."""
        return {
            "node": self.get_id(),
            "moniker": self.core.validator.moniker,
            "provenance": self.telemetry.provenance.stats(),
            "records": self.telemetry.provenance.export(limit=limit),
        }

    def get_suspects(self) -> Dict[str, object]:
        """/suspects service payload: the sentry's per-peer misbehavior
        ledger + equivocation proofs, with peers annotated by moniker so
        operators can tell who is who (docs/robustness.md)."""
        body = self.core.sentry.suspects()
        by_id = self.core.hg.store.repertoire_by_id()
        for pid_s, entry in body["peers"].items():
            peer = by_id.get(int(pid_s))
            if peer is not None:
                entry["moniker"] = peer.moniker
                entry["pub_key"] = peer.pub_key_hex
        return body

    def _log_stats(self) -> None:
        # guard: get_stats() walks every subsystem (selector sweep,
        # commit-latency summary) — don't build it just to drop the line
        if self.logger.isEnabledFor(logging.DEBUG):
            self.logger.debug("stats: %s", self.get_stats())
