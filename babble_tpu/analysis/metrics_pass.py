"""metrics — instrument catalog ↔ docs table parity.

The absorbed metricslint (formerly the whole of ``obs/lint.py``, which
remains as a compat shim): the instrument catalog
(``obs.catalog.CATALOG``) and the table between
``<!-- metrics-table-start/end -->`` in docs/observability.md must match
exactly, in both directions — a new instrument cannot ship undocumented,
and a stale docs row cannot outlive its instrument.
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Set

from .core import SourceFile, Violation, register

DOCS_PATH = "docs/observability.md"
START = "<!-- metrics-table-start -->"
END = "<!-- metrics-table-end -->"
_ROW = re.compile(r"^\|\s*`([a-zA-Z_][a-zA-Z0-9_]*)`")


def documented_names(text: str) -> Set[str]:
    try:
        body = text.split(START, 1)[1].split(END, 1)[0]
    except IndexError:
        raise SystemExit(
            f"metrics lint: marker comments {START!r}/{END!r} not found "
            "in the docs file"
        )
    names = set()
    for line in body.splitlines():
        m = _ROW.match(line.strip())
        if m:
            names.add(m.group(1))
    return names


def check(path: str) -> List[Violation]:
    from ..obs.catalog import CATALOG

    with open(path, encoding="utf-8") as f:
        text = f.read()
    if START not in text:
        return [
            Violation(
                path, 1, "metrics",
                f"marker comments {START!r}/{END!r} not found",
            )
        ]
    marker_line = text[: text.index(START)].count("\n") + 1
    docs = documented_names(text)
    cataloged = {i.name for i in CATALOG}
    out: List[Violation] = []
    for n in sorted(cataloged - docs):
        out.append(
            Violation(
                path, marker_line, "metrics",
                f"registered instrument `{n}` missing from the docs table",
            )
        )
    for n in sorted(docs - cataloged):
        out.append(
            Violation(
                path, marker_line, "metrics",
                f"documented name `{n}` missing from "
                "babble_tpu/obs/catalog.py",
            )
        )
    return out


@register("metrics")
def run_pass(files: List[SourceFile], root: str) -> List[Violation]:
    path = os.path.join(root, DOCS_PATH)
    if not os.path.exists(path):
        # fixture runs without a docs tree skip the contract
        return []
    vs = check(path)
    # report repo-relative like every other pass
    for v in vs:
        v.path = DOCS_PATH
    return vs


# -- obs/lint.py compat surface ---------------------------------------------

def run(path: str) -> int:
    """The original ``obs.lint.run`` contract: print mismatches to
    stderr, return 1 on drift, 0 (with a summary line) when clean —
    and raise SystemExit when the marker comments are missing
    entirely (callers and tests rely on that distinction)."""
    from ..obs.catalog import CATALOG

    with open(path, encoding="utf-8") as f:
        documented_names(f.read())  # raises SystemExit on no markers
    vs = check(path)
    for v in vs:
        print(f"metrics lint: {v.message} ({path})", file=sys.stderr)
    if vs:
        return 1
    print(
        f"metrics lint ok: {len(CATALOG)} instruments match "
        f"between catalog and {path}"
    )
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else "docs/observability.md"
    return run(path)
