"""locks — the static lock-discipline pass.

PR 1/9 shrank the core lock to the insert tail: decode + batch-verify
run lock-free and only ``insert + DivideRounds`` (and the commit tail)
hold ``core_lock``. Nothing enforced that — one blocking call (a socket
op, a sleep, an RPC send, a native batch-verify) slipped under the lock
in a later PR would silently re-serialize the whole node. This pass
builds the static lock graph and flags:

1. **blocking-while-core-locked** — a blocking primitive reachable
   (through the intra-project call graph) from a ``with <core lock>:``
   region;
2. **acquisition-order cycles** — ``with`` nesting (direct or through
   called functions) that produces both an A→B and a B→A edge between
   named locks.

The model is deliberately modest and its limits are documented
(docs/static_analysis.md §Lock model): only ``with``-statement regions
are analyzed (bare ``.acquire()``/``.release()`` pairs are invisible);
calls resolve by *name* — ``self.<m>()`` to the same class,
``self.<attr>.<m>()`` through the ATTR_TYPES convention table,
bare-name calls to same-module functions; everything else (callbacks,
dynamic dispatch, cross-process) is out of scope. The runtime
lock-order recorder (``common/lockcheck.py``, ``BABBLE_LOCKCHECK=1``)
validates the same edge set empirically under the chaos and sim soaks,
closing the gap from the other side.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import SourceFile, Violation, register

#: attribute name (last path segment) -> lock name, matched anywhere —
#: the core lock travels as ``self.core_lock`` / ``node.core_lock``.
GLOBAL_LOCK_ATTRS: Dict[str, str] = {"core_lock": "core"}

#: (path suffix, class name, attr) -> lock name, for the ``self._lock``
#: convention inside known lock-owning classes.
CLASS_LOCKS: Dict[Tuple[str, str, str], str] = {
    ("mempool/mempool.py", "Mempool", "_lock"): "mempool",
    ("node/sentry.py", "Sentry", "_lock"): "sentry",
    # NOTE: the subscription hub (client/subhub.py) is deliberately
    # absent — it is single-selector-threaded with a non-blocking wake
    # pipe, so there is no hub lock to model (docs/static_analysis.md).
    ("node/pipeline.py", "SyncPipeline", "_lock"): "pipeline",
    ("hashgraph/sweep_batcher.py", "SweepBatcher", "_lock"): "batcher",
}

#: ``self.<attr>`` -> class the attribute conventionally holds, for
#: one-hop cross-object call resolution. A convention table, not type
#: inference — docs/static_analysis.md spells out the limits.
ATTR_TYPES: Dict[str, Tuple[str, str]] = {
    "core": ("node/core.py", "Core"),
    "mempool": ("mempool/mempool.py", "Mempool"),
    "sentry": ("node/sentry.py", "Sentry"),
    "pipeline": ("node/pipeline.py", "SyncPipeline"),
}

#: locks whose held regions must stay free of blocking calls. Order
#: edges are recorded for EVERY named lock; the blocking check applies
#: to the core lock (the consensus hot path) only.
BLOCK_CHECK_LOCKS = {"core"}

#: callee attribute names treated as blocking primitives
SLEEP_FNS = {"sleep"}
SOCKET_FNS = {
    "recv",
    "recv_into",
    "send",
    "sendall",
    "connect",
    "accept",
    "makefile",
    "create_connection",
    "dial",
}
RPC_FNS = {
    "sync",
    "eager_sync",
    "fast_forward",
    "join",
    "request_sync",
    "request_eager_sync",
    "request_fast_forward",
}
NATIVE_VERIFY_FNS = {"verify_batch", "batch_verify_events"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'self.core.sync' for an attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_name(path: str, cls: Optional[str], expr: ast.AST) -> Optional[str]:
    dotted = _dotted(expr)
    if not dotted:
        return None
    last = dotted.rsplit(".", 1)[-1]
    if last in GLOBAL_LOCK_ATTRS:
        return GLOBAL_LOCK_ATTRS[last]
    if cls and dotted == f"self.{last}":
        for (suffix, kcls, attr), name in CLASS_LOCKS.items():
            if path.endswith(suffix) and cls == kcls and last == attr:
                return name
    return None


def _blocking_desc(callee: str, dotted: Optional[str]) -> Optional[str]:
    """Classify a call as a blocking primitive, or None."""
    if callee in SLEEP_FNS:
        return f"sleep ({dotted or callee})"
    if callee in SOCKET_FNS:
        return f"socket op {dotted or callee}()"
    if callee in NATIVE_VERIFY_FNS:
        return f"native batch-verify {dotted or callee}()"
    if dotted and callee in RPC_FNS:
        recv = dotted.rsplit(".", 2)
        # RPC names only count on a transport-ish receiver: Core.sync()
        # is the local ingest, self.trans.sync() is a network round-trip
        if len(recv) >= 2 and recv[-2] in ("trans", "transport", "network"):
            return f"RPC send {dotted}()"
    return None


FuncKey = Tuple[str, Optional[str], str]  # (path, class, func)


def _resolve_callee(
    dotted: Optional[str], path: str, cls: Optional[str]
) -> Optional[FuncKey]:
    """Name-based callee resolution, shared by both sweeps: ``self.<m>``
    to the same class, ``self.<attr>.<m>`` to the ATTR_TYPES hint (as a
    path-SUFFIX key — ``canon()`` in the closure resolves it against the
    real file set), bare names to same-module functions."""
    if not dotted:
        return None
    parts = dotted.split(".")
    if parts[0] == "self" and len(parts) == 2 and cls:
        return (path, cls, parts[1])
    if parts[0] == "self" and len(parts) == 3:
        hint = ATTR_TYPES.get(parts[1])
        if hint:
            return (hint[0], hint[1], parts[2])
    if len(parts) == 1:
        return (path, None, parts[0])
    return None



@dataclass
class _FuncFacts:
    key: FuncKey
    line: int = 0
    #: blocking primitives called directly: (line, desc)
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    #: locks acquired directly via ``with``
    acquires: Set[str] = field(default_factory=set)
    #: resolved intra-project callees
    callees: Set[FuncKey] = field(default_factory=set)


class _Collector(ast.NodeVisitor):
    """First sweep: per-function facts for the whole file set."""

    def __init__(self, sf: SourceFile, funcs: Dict[FuncKey, _FuncFacts]):
        self.sf = sf
        self.funcs = funcs
        self.cls: Optional[str] = None
        self.fn: Optional[_FuncFacts] = None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    def _visit_func(self, node) -> None:
        prev = self.fn
        key = (self.sf.path, self.cls, node.name)
        self.fn = self.funcs.setdefault(key, _FuncFacts(key, node.lineno))
        self.generic_visit(node)
        self.fn = prev

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_With(self, node: ast.With) -> None:
        if self.fn is not None:
            for item in node.items:
                ln = _lock_name(self.sf.path, self.cls, item.context_expr)
                if ln:
                    self.fn.acquires.add(ln)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.fn is not None:
            dotted = _dotted(node.func)
            callee = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else (node.func.id if isinstance(node.func, ast.Name) else "")
            )
            desc = _blocking_desc(callee, dotted)
            if desc:
                self.fn.blocking.append((node.lineno, desc))
            resolved = self._resolve(dotted)
            if resolved:
                self.fn.callees.add(resolved)
        self.generic_visit(node)

    def _resolve(self, dotted: Optional[str]) -> Optional[FuncKey]:
        return _resolve_callee(dotted, self.sf.path, self.cls)


def _closure(funcs: Dict[FuncKey, _FuncFacts]):
    """Fixpoint: transitive blocking witness + transitive lock set."""
    blocks: Dict[FuncKey, Optional[str]] = {}
    locks: Dict[FuncKey, Set[str]] = {}

    def canon(key: FuncKey) -> Optional[FuncKey]:
        if key in funcs:
            return key
        # ATTR_TYPES stores a suffix until resolved against real paths
        path, cls, name = key
        for k in funcs:
            if k[1] == cls and k[2] == name and k[0].endswith(path):
                return k
        return None

    for k, f in funcs.items():
        blocks[k] = f.blocking[0][1] if f.blocking else None
        locks[k] = set(f.acquires)
    changed = True
    while changed:
        changed = False
        for k, f in funcs.items():
            for c in f.callees:
                ck = canon(c)
                if ck is None:
                    continue
                if blocks[k] is None and blocks.get(ck):
                    blocks[k] = (
                        f"{ck[1] or ck[0]}.{ck[2]} → {blocks[ck]}"
                    )
                    changed = True
                add = locks.get(ck, set()) - locks[k]
                if add:
                    locks[k] |= add
                    changed = True
    return blocks, locks, canon


class _RegionChecker(ast.NodeVisitor):
    """Second sweep: walk each ``with <lock>`` region with the held-lock
    stack, emitting blocking violations and order edges."""

    def __init__(self, sf, funcs, blocks, locks, canon, edges, out):
        self.sf = sf
        self.funcs = funcs
        self.blocks = blocks
        self.locks = locks
        self.canon = canon
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = edges
        self.out: List[Violation] = out
        self.cls: Optional[str] = None
        self.held: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self.cls = self.cls, node.name
        self.generic_visit(node)
        self.cls = prev

    def _visit_func(self, node) -> None:
        # a nested function body does not run under the enclosing lock
        prev, self.held = self.held, []
        self.generic_visit(node)
        self.held = prev

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            ln = _lock_name(self.sf.path, self.cls, item.context_expr)
            if ln:
                for h in self.held:
                    if h != ln:
                        self.edges.setdefault(
                            (h, ln), (self.sf.path, node.lineno)
                        )
                entered.append(ln)
        self.held.extend(entered)
        self.generic_visit(node)
        del self.held[len(self.held) - len(entered):]

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            dotted = _dotted(node.func)
            callee = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else (node.func.id if isinstance(node.func, ast.Name) else "")
            )
            desc = _blocking_desc(callee, dotted)
            checked = [h for h in self.held if h in BLOCK_CHECK_LOCKS]
            if desc and checked:
                self.out.append(
                    Violation(
                        self.sf.path,
                        node.lineno,
                        "locks",
                        f"blocking call under the {checked[-1]} lock: "
                        f"{desc}",
                    )
                )
            resolved = self._resolve(dotted)
            ck = self.canon(resolved) if resolved else None
            if ck is not None:
                witness = self.blocks.get(ck)
                if witness and checked:
                    self.out.append(
                        Violation(
                            self.sf.path,
                            node.lineno,
                            "locks",
                            f"call under the {checked[-1]} lock reaches a "
                            f"blocking primitive: {dotted}() → {witness}",
                        )
                    )
                for lk in self.locks.get(ck, ()):
                    for h in self.held:
                        if h != lk:
                            self.edges.setdefault(
                                (h, lk), (self.sf.path, node.lineno)
                            )
        self.generic_visit(node)

    def _resolve(self, dotted: Optional[str]) -> Optional[FuncKey]:
        return _resolve_callee(dotted, self.sf.path, self.cls)


def _find_cycles(edges: Dict[Tuple[str, str], Tuple[str, int]]):
    """Every elementary 2-cycle and longer cycle via DFS; 2-cycles are
    the common inversion and reported pairwise."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles: List[List[str]] = []
    seen_pairs = set()
    for (a, b) in sorted(edges):
        if (b, a) in edges and (b, a) not in seen_pairs:
            cycles.append([a, b, a])
            seen_pairs.add((a, b))
    # longer cycles: DFS with path tracking
    def dfs(start: str, node: str, path: List[str], visited: Set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 2:
                cyc = path + [start]
                if set(cyc) not in [set(c) for c in cycles]:
                    cycles.append(cyc)
            elif nxt not in visited:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for n in sorted(graph):
        dfs(n, n, [n], {n})
    return cycles


@register("locks")
def run(files: List[SourceFile], root: str) -> List[Violation]:
    funcs: Dict[FuncKey, _FuncFacts] = {}
    for sf in files:
        if sf.tree is not None:
            _Collector(sf, funcs).visit(sf.tree)
    blocks, locks, canon = _closure(funcs)
    out: List[Violation] = []
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for sf in files:
        if sf.tree is not None:
            _RegionChecker(
                sf, funcs, blocks, locks, canon, edges, out
            ).visit(sf.tree)
    for cyc in _find_cycles(edges):
        first = edges.get((cyc[0], cyc[1])) or next(iter(edges.values()))
        out.append(
            Violation(
                first[0],
                first[1],
                "locks",
                "lock acquisition-order cycle: " + " → ".join(cyc)
                + " (each edge = a site acquiring the later lock while "
                "holding the earlier)",
            )
        )
    return out


def static_edges(files: List[SourceFile]) -> List[str]:
    """The static order-edge set ("a->b" strings) — compared against the
    runtime recorder's observed edges in tests."""
    funcs: Dict[FuncKey, _FuncFacts] = {}
    for sf in files:
        if sf.tree is not None:
            _Collector(sf, funcs).visit(sf.tree)
    blocks, locks, canon = _closure(funcs)
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    sink: List[Violation] = []
    for sf in files:
        if sf.tree is not None:
            _RegionChecker(
                sf, funcs, blocks, locks, canon, edges, sink
            ).visit(sf.tree)
    return sorted(f"{a}->{b}" for (a, b) in edges)
