"""knobs — the Config ↔ CLI ↔ toml ↔ docs knob-parity pass.

PR 8 shipped ``watchdog_interval_s`` with a ``_RUN_FLAGS`` entry but no
``add_argument`` — ``--watchdog-interval`` silently didn't exist until a
review caught it. This pass makes that whole drift class mechanical:

1. every ``Config`` field is reachable from operators: it has a
   ``_RUN_FLAGS`` entry (which IS the toml key — the toml layer iterates
   ``_RUN_FLAGS``). Runtime injection points (``clock``, ``sim_seed``)
   carry ``# lint: allow(knobs: …)`` where they are defined;
2. every ``_RUN_FLAGS`` entry maps to a real ``Config`` field (no
   dangling attrs);
3. every ``_RUN_FLAGS`` key has a matching run-subparser
   ``add_argument`` dest (toml-only knobs — negative-polarity booleans
   like ``adaptive_gossip`` — carry an allow on the dict line);
4. every run-subparser ``add_argument`` dest feeds ``_RUN_FLAGS`` or is
   a declared CLI-only argument (proxy endpoints, ``--no-*`` toggles);
5. every ``DEFAULT_*`` constant in config.py is read somewhere in the
   package (an orphaned default is drift waiting to happen);
6. the knob table in docs/design.md (between
   ``<!-- knob-table-start/end -->``) lists every run flag and every
   toml-only key, and nothing else — two-way, the metricslint contract
   applied to knobs.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import SourceFile, Violation, register

CONFIG_PATH = "babble_tpu/config/config.py"
CLI_PATH = "babble_tpu/cli/main.py"
DOCS_PATH = "docs/design.md"

#: run-subparser arguments that are deliberately CLI-only (not Config
#: knobs): proxy wiring consumed before Config is built, and
#: negative-polarity toggles whose positive knob is toml-routed.
CLI_ONLY_DESTS = {
    "datadir",  # consumed as the _RUN_FLAGS "datadir" key
    "proxy_listen",
    "client_connect",
    "inmem_dummy",
    "no_adaptive",
    "no_gossip_pipeline",
    "no_prune_vacuum",
}

KNOB_START = "<!-- knob-table-start -->"
KNOB_END = "<!-- knob-table-end -->"
_KNOB_ROW = re.compile(r"^\|\s*`(--[a-z0-9-]+|[a-z_]+ \(toml\))`")


def _config_fields(sf: SourceFile) -> Dict[str, int]:
    """Config dataclass field name -> line."""
    fields: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    fields[stmt.target.id] = stmt.lineno
    return fields


def _default_constants(sf: SourceFile) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id.startswith("DEFAULT_"):
                    out[t.id] = node.lineno
    return out


def _run_flags(sf: SourceFile) -> Dict[str, Tuple[str, int]]:
    """_RUN_FLAGS flag key -> (Config attr, line of the dict entry)."""
    out: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "_RUN_FLAGS"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if (
                    isinstance(k, ast.Constant)
                    and isinstance(v, ast.Tuple)
                    and v.elts
                    and isinstance(v.elts[0], ast.Constant)
                ):
                    out[k.value] = (v.elts[0].value, k.lineno)
    return out


def _run_arguments(sf: SourceFile) -> Dict[str, Tuple[str, int]]:
    """run-subparser dest -> (first long option string, line)."""
    run_vars: Set[str] = set()
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Attribute)
            and node.value.func.attr == "add_parser"
            and node.value.args
            and isinstance(node.value.args[0], ast.Constant)
            and node.value.args[0].value == "run"
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    run_vars.add(t.id)
    dests: Dict[str, Tuple[str, int]] = {}
    for node in ast.walk(sf.tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in run_vars
        ):
            opts = [
                a.value
                for a in node.args
                if isinstance(a, ast.Constant) and isinstance(a.value, str)
            ]
            long_opts = [o for o in opts if o.startswith("--")]
            if not long_opts:
                continue
            dest: Optional[str] = None
            for kw in node.keywords:
                if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                    dest = kw.value.value
            if dest is None:
                dest = long_opts[0].lstrip("-").replace("-", "_")
            dests[dest] = (long_opts[0], node.lineno)
    return dests


def _documented_knobs(root: str) -> Tuple[Set[str], int, Optional[str]]:
    """Backticked first-column entries of the knob table, the marker
    line, and an error when the table is missing."""
    path = os.path.join(root, DOCS_PATH)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError as err:
        return set(), 1, f"knob table source unreadable: {err}"
    if KNOB_START not in text or KNOB_END not in text:
        return (
            set(),
            1,
            f"marker comments {KNOB_START!r}/{KNOB_END!r} not found in "
            f"{DOCS_PATH}",
        )
    start_line = text[: text.index(KNOB_START)].count("\n") + 1
    body = text.split(KNOB_START, 1)[1].split(KNOB_END, 1)[0]
    rows: Set[str] = set()
    for line in body.splitlines():
        m = _KNOB_ROW.match(line.strip())
        if m:
            rows.add(m.group(1))
    return rows, start_line, None


@register("knobs")
def run(files: List[SourceFile], root: str) -> List[Violation]:
    cfg = next((f for f in files if f.path == CONFIG_PATH), None)
    cli = next((f for f in files if f.path == CLI_PATH), None)
    out: List[Violation] = []
    if cfg is None or cfg.tree is None or cli is None or cli.tree is None:
        # fixture runs that scan only snippets skip the knob contract
        return out
    fields = _config_fields(cfg)
    flags = _run_flags(cli)
    dests = _run_arguments(cli)
    flag_attrs = {attr for attr, _ in flags.values()}

    # (1) every Config field has a CLI/toml route
    for name, line in sorted(fields.items()):
        if name not in flag_attrs:
            out.append(
                Violation(
                    cfg.path,
                    line,
                    "knobs",
                    f"Config field {name!r} has no _RUN_FLAGS entry — "
                    "operators can't reach it from the CLI or babble.toml "
                    "(add the flag, or allow() a runtime injection point)",
                )
            )
    # (2) no dangling _RUN_FLAGS attrs
    for flag, (attr, line) in sorted(flags.items()):
        if attr not in fields:
            out.append(
                Violation(
                    cli.path,
                    line,
                    "knobs",
                    f"_RUN_FLAGS maps {flag!r} to Config.{attr}, which "
                    "does not exist",
                )
            )
    # (3) every _RUN_FLAGS key is parseable from the CLI
    for flag, (_attr, line) in sorted(flags.items()):
        if flag not in dests:
            out.append(
                Violation(
                    cli.path,
                    line,
                    "knobs",
                    f"_RUN_FLAGS key {flag!r} has no run-subparser "
                    f"add_argument dest — '--{flag.replace('_', '-')}' "
                    "silently doesn't exist (the --watchdog-interval "
                    "drift class); add the flag or allow() a toml-only "
                    "knob",
                )
            )
    # (4) every run argument feeds Config or is declared CLI-only
    for dest, (opt, line) in sorted(dests.items()):
        if dest not in flags and dest not in CLI_ONLY_DESTS:
            out.append(
                Violation(
                    cli.path,
                    line,
                    "knobs",
                    f"run argument {opt} (dest {dest!r}) feeds neither "
                    "_RUN_FLAGS nor the CLI-only list — its value is "
                    "dropped on the floor",
                )
            )
    # (5) orphaned DEFAULT_* constants
    consts = _default_constants(cfg)
    used: Set[str] = set()
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                if node.id.startswith("DEFAULT_"):
                    used.add(node.id)
    for name, line in sorted(consts.items()):
        if name not in used:
            out.append(
                Violation(
                    cfg.path,
                    line,
                    "knobs",
                    f"orphaned constant {name}: assigned in config.py but "
                    "read nowhere in the package",
                )
            )
    # (6) docs knob table, two-way
    documented, marker_line, err = _documented_knobs(root)
    if err:
        out.append(Violation(DOCS_PATH, marker_line, "knobs", err))
        return out
    expected: Set[str] = set()
    for dest, (opt, _line) in dests.items():
        expected.add(opt)
    for flag in flags:
        if flag not in dests:
            expected.add(f"{flag} (toml)")  # toml-only knob
    for name in sorted(expected - documented):
        out.append(
            Violation(
                DOCS_PATH,
                marker_line,
                "knobs",
                f"knob `{name}` missing from the docs table",
            )
        )
    for name in sorted(documented - expected):
        out.append(
            Violation(
                DOCS_PATH,
                marker_line,
                "knobs",
                f"documented knob `{name}` does not exist in "
                f"{CLI_PATH}",
            )
        )
    return out
