"""babblelint — the project-wide static-analysis suite.

The paper's determinism and liveness claims rest on invariants the code
historically enforced only by convention: every subsystem must route
time and randomness through ``Config.clock`` / ``Config.seeded_rng`` so
sim runs replay byte-identically (docs/simulation.md), the core lock
must cover only the insert tail and never a blocking call
(docs/gossip.md), and every ``Config`` knob must stay reachable from the
CLI, the toml layer, and the docs (the ``--watchdog-interval`` drift
class). ``python -m babble_tpu.analysis`` checks all of it mechanically
— the way production consensus systems back their TLA+-adjacent
invariants with lint layers (docs/static_analysis.md).

Passes (each importable standalone):

- ``clock``   — clock/RNG discipline (analysis/clock_pass.py)
- ``locks``   — static lock graph + blocking-while-locked
  (analysis/lock_pass.py), validated at runtime by the BABBLE_LOCKCHECK
  recorder in common/lockcheck.py
- ``knobs``   — Config ↔ CLI ↔ toml ↔ docs knob parity
  (analysis/knob_pass.py)
- ``metrics`` — instrument catalog ↔ docs table (analysis/metrics_pass.py,
  the absorbed obs/lint.py, which remains as a compat shim)

Inline suppressions: ``# lint: allow(<pass>: <reason>)`` on the
violating line or the line directly above. Allows are themselves linted
— one that matches no violation is an error, so the allowlist can't rot.
"""

from .core import (  # noqa: F401
    Allow,
    SourceFile,
    Violation,
    load_tree,
    parse_allows,
    run_passes,
)
