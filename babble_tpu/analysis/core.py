"""Shared file-walker / report / suppression core for babblelint.

Every pass consumes the same pre-parsed :class:`SourceFile` objects (one
``ast`` parse per file per run, shared by all passes) and emits
:class:`Violation` records. The runner then applies the inline
suppression contract:

- ``# lint: allow(<pass>: <reason>)`` suppresses violations of ``<pass>``
  on the SAME line, or — when the comment stands alone — on the next
  line that carries code.
- an allow that suppressed nothing when its pass ran is itself a
  violation (``stale-allow``): the allowlist cannot rot silently.
- an allow naming an unknown pass is a violation (``unknown-pass``).

A reason is mandatory — an allow is a documented decision, not an
escape hatch.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Sequence

# ``# lint: allow(clock: recv_ts is a real arrival stamp)``
_ALLOW_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*([a-z_]+)\s*:\s*([^)]+?)\s*\)"
)


@dataclass
class Violation:
    """One finding: ``path:line: [pass] message``."""

    path: str
    line: int
    passname: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.passname}] {self.message}"


@dataclass
class Allow:
    """One parsed inline suppression."""

    path: str
    line: int  # line the comment sits on
    passname: str
    reason: str
    #: lines this allow covers: its own line, plus — for a comment-only
    #: line — the next line carrying code
    covers: tuple = ()
    consumed: bool = False


@dataclass
class SourceFile:
    """One parsed source file, shared by every pass in a run."""

    path: str  # repo-relative, forward slashes
    text: str
    lines: List[str] = field(default_factory=list)
    tree: Optional[ast.AST] = None
    parse_error: Optional[str] = None
    allows: List[Allow] = field(default_factory=list)

    @staticmethod
    def from_text(relpath: str, text: str) -> "SourceFile":
        """Build from a string — fixture snippets and the self-proof."""
        sf = SourceFile(path=relpath.replace(os.sep, "/"), text=text)
        sf.lines = text.splitlines()
        try:
            sf.tree = ast.parse(text)
        except SyntaxError as err:
            sf.parse_error = f"syntax error: {err}"
        sf.allows = parse_allows(sf)
        return sf

    @staticmethod
    def load(abspath: str, relpath: str) -> "SourceFile":
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        sf = SourceFile(path=relpath.replace(os.sep, "/"), text=text)
        sf.lines = text.splitlines()
        try:
            sf.tree = ast.parse(text)
        except SyntaxError as err:  # surfaced as a violation by the runner
            sf.parse_error = f"syntax error: {err}"
        sf.allows = parse_allows(sf)
        return sf


def parse_allows(sf: SourceFile) -> List[Allow]:
    """Extract ``# lint: allow(pass: reason)`` comments and compute the
    lines each one covers."""
    allows: List[Allow] = []
    for i, raw in enumerate(sf.lines, start=1):
        m = _ALLOW_RE.search(raw)
        if not m:
            continue
        covers = [i]
        code_before = raw[: m.start()].strip()
        if not code_before:
            # comment-only line: cover the next line that carries code
            j = i + 1
            while j <= len(sf.lines) and not sf.lines[j - 1].strip():
                j += 1
            if j <= len(sf.lines):
                covers.append(j)
        allows.append(
            Allow(
                path=sf.path,
                line=i,
                passname=m.group(1),
                reason=m.group(2),
                covers=tuple(covers),
            )
        )
    return allows


# -- tree loading -----------------------------------------------------------

#: directories never scanned (generated, caches, vendored) — plus the
#: lint suite itself: its docstrings and self-proof fixtures quote the
#: allow syntax and violation shapes verbatim, which must not parse as
#: live suppressions or findings.
SKIP_DIRS = {"__pycache__", ".git", "dist", "build", "node_modules",
             "analysis"}


def repo_root() -> str:
    """The repository root: the directory holding the ``babble_tpu``
    package this module was imported from."""
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


def load_tree(
    root: Optional[str] = None, paths: Optional[Sequence[str]] = None
) -> List[SourceFile]:
    """Load every ``.py`` under ``babble_tpu/`` (plus ``cli``'s siblings)
    relative to ``root``, or exactly ``paths`` when given. Tests pass
    explicit fixture paths; CI runs the default walk."""
    root = root or repo_root()
    files: List[SourceFile] = []
    if paths:
        for p in paths:
            ab = p if os.path.isabs(p) else os.path.join(root, p)
            files.append(SourceFile.load(ab, os.path.relpath(ab, root)))
        return files
    pkg = os.path.join(root, "babble_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                ab = os.path.join(dirpath, fn)
                files.append(SourceFile.load(ab, os.path.relpath(ab, root)))
    return files


# -- pass registry ----------------------------------------------------------

#: name -> callable(files, root) -> list[Violation]; populated by
#: register() at import time in __main__ (passes stay import-light so
#: fixtures can run one pass without loading the rest).
PassFn = Callable[[List[SourceFile], str], List[Violation]]
REGISTRY: Dict[str, PassFn] = {}


def register(name: str) -> Callable[[PassFn], PassFn]:
    def deco(fn: PassFn) -> PassFn:
        REGISTRY[name] = fn
        return fn

    return deco


def apply_allows(
    passname: str, files: List[SourceFile], violations: List[Violation]
) -> List[Violation]:
    """Suppress violations covered by a matching allow; emit stale-allow
    violations for allows of ``passname`` that suppressed nothing."""
    by_file: Dict[str, List[Allow]] = {}
    for sf in files:
        for a in sf.allows:
            if a.passname == passname:
                by_file.setdefault(sf.path, []).append(a)
    kept: List[Violation] = []
    for v in violations:
        suppressed = False
        for a in by_file.get(v.path, ()):
            if v.line in a.covers:
                a.consumed = True
                suppressed = True
        if not suppressed:
            kept.append(v)
    for allows in by_file.values():
        for a in allows:
            if not a.consumed:
                kept.append(
                    Violation(
                        a.path,
                        a.line,
                        passname,
                        f"stale allow: no {passname} violation on "
                        f"line(s) {'/'.join(map(str, a.covers))} to "
                        f"suppress (reason was: {a.reason!r}) — remove "
                        "the comment or restore the site it documented",
                    )
                )
    return kept


def check_unknown_allows(files: List[SourceFile]) -> List[Violation]:
    """An allow naming a pass that doesn't exist is always an error."""
    out: List[Violation] = []
    for sf in files:
        for a in sf.allows:
            if a.passname not in REGISTRY:
                out.append(
                    Violation(
                        sf.path,
                        a.line,
                        "allow",
                        f"unknown pass {a.passname!r} in allow comment "
                        f"(known: {', '.join(sorted(REGISTRY))})",
                    )
                )
    return out


def run_passes(
    names: Optional[Iterable[str]] = None,
    root: Optional[str] = None,
    paths: Optional[Sequence[str]] = None,
    files: Optional[List[SourceFile]] = None,
) -> List[Violation]:
    """Run the named passes (default: all registered) over one shared
    parse of the tree, applying the suppression contract per pass."""
    # importing the pass modules populates REGISTRY
    from . import clock_pass, knob_pass, lock_pass, metrics_pass  # noqa: F401

    root = root or repo_root()
    if files is None:
        files = load_tree(root, paths)
    selected = list(names) if names else sorted(REGISTRY)
    out: List[Violation] = []
    for sf in files:
        if sf.parse_error:
            out.append(Violation(sf.path, 1, "parse", sf.parse_error))
    out.extend(check_unknown_allows(files))
    for name in selected:
        if name not in REGISTRY:
            raise SystemExit(
                f"babblelint: unknown pass {name!r} "
                f"(known: {', '.join(sorted(REGISTRY))})"
            )
        vs = REGISTRY[name](files, root)
        out.extend(apply_allows(name, files, vs))
    out.sort(key=lambda v: (v.path, v.line, v.passname))
    return out


def report(violations: List[Violation], stream=None) -> int:
    stream = stream or sys.stderr
    for v in violations:
        print(v.render(), file=stream)
    return 1 if violations else 0
