"""babblelint entry point.

Usage::

    python -m babble_tpu.analysis                 # all passes, whole tree
    python -m babble_tpu.analysis --pass clock     # one pass
    python -m babble_tpu.analysis path/to/file.py  # explicit files
    python -m babble_tpu.analysis --self-proof     # prove the teeth

Exit codes: 0 clean, 1 violations, 2 usage error. ``--self-proof``
injects one violation per pass (plus a stale allow) into synthetic
sources and exits nonzero unless EVERY pass catches its injection — the
perfgate ``--inject-regression`` pattern: a toothless linter fails the
build, not the code it was supposed to guard.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

from .core import REGISTRY, SourceFile, load_tree, report, run_passes

# -- self-proof fixtures -----------------------------------------------------

_CLOCK_BAD = """\
import time
import random


def jitter(interval):
    time.sleep(0.1)
    return interval + random.random() * interval
"""

_LOCKS_BAD = """\
import time


class Node:
    def gossip(self):
        with self.core_lock:
            time.sleep(0.5)
"""

_KNOBS_CONFIG_BAD = """\
from dataclasses import dataclass

DEFAULT_ORPHANED_KNOB = 42


@dataclass
class Config:
    ghost_knob: int = 0
"""

_KNOBS_CLI_BAD = """\
_RUN_FLAGS = {
    "dangling": ("not_a_field", str),
}
"""

_METRICS_DOCS_BAD = """\
<!-- metrics-table-start -->
| `this_instrument_does_not_exist` | counter | - | node | bogus |
<!-- metrics-table-end -->
"""

_STALE_ALLOW = """\
import os

# lint: allow(clock: this allow matches nothing and must be flagged)
x = os.getcwd()
"""


def self_proof() -> int:
    """Each pass must catch its injected violation; the allow layer must
    catch a stale allow. Prints one line per pass; exit 0 = all fired."""
    from . import clock_pass, knob_pass, lock_pass, metrics_pass
    from .core import apply_allows

    failures = []

    def fired(name: str, violations, want: str = "") -> None:
        hit = [v for v in violations if want in v.message]
        status = "fired" if hit else "TOOTHLESS"
        print(f"self-proof [{name}]: {status} "
              f"({len(violations)} violation(s))")
        if not hit:
            failures.append(name)

    files = [SourceFile.from_text("babble_tpu/node/_inject.py", _CLOCK_BAD)]
    fired("clock", clock_pass.run(files, "."))

    files = [SourceFile.from_text("babble_tpu/node/_inject.py", _LOCKS_BAD)]
    fired("locks", lock_pass.run(files, "."), "blocking call")

    with tempfile.TemporaryDirectory() as td:
        os.makedirs(os.path.join(td, "docs"))
        with open(os.path.join(td, "docs", "design.md"), "w") as f:
            f.write("<!-- knob-table-start -->\n<!-- knob-table-end -->\n")
        files = [
            SourceFile.from_text(knob_pass.CONFIG_PATH, _KNOBS_CONFIG_BAD),
            SourceFile.from_text(knob_pass.CLI_PATH, _KNOBS_CLI_BAD),
        ]
        fired("knobs", knob_pass.run(files, td), "ghost_knob")

        with open(os.path.join(td, "docs", "observability.md"), "w") as f:
            f.write(_METRICS_DOCS_BAD)
        fired(
            "metrics",
            metrics_pass.check(
                os.path.join(td, "docs", "observability.md")
            ),
            "this_instrument_does_not_exist",
        )

    files = [SourceFile.from_text("babble_tpu/node/_inject.py", _STALE_ALLOW)]
    fired(
        "stale-allow",
        apply_allows("clock", files, clock_pass.run(files, ".")),
        "stale allow",
    )

    if failures:
        print(
            f"self-proof FAILED: pass(es) did not fire: "
            f"{', '.join(failures)}",
            file=sys.stderr,
        )
        return 1
    print("self-proof ok: every pass caught its injected violation")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m babble_tpu.analysis",
        description="babblelint — project-wide static analysis "
        "(docs/static_analysis.md)",
    )
    p.add_argument(
        "--pass",
        dest="passes",
        default=None,
        help="comma-separated pass names (default: all)",
    )
    p.add_argument("--root", default=None, help="repository root")
    p.add_argument("--list", action="store_true", help="list passes")
    p.add_argument(
        "--self-proof",
        action="store_true",
        help="inject one violation per pass; exit nonzero unless every "
        "pass fires",
    )
    p.add_argument("paths", nargs="*", help="explicit files (default: tree)")
    args = p.parse_args(argv)

    if args.self_proof:
        return self_proof()
    # populate the registry before --list
    from . import clock_pass, knob_pass, lock_pass, metrics_pass  # noqa: F401

    if args.list:
        for name in sorted(REGISTRY):
            print(name)
        return 0
    names = args.passes.split(",") if args.passes else None
    root = args.root
    files = load_tree(root, args.paths or None)
    violations = run_passes(names=names, root=root, files=files)
    rc = report(violations)
    if rc == 0:
        ran = ",".join(sorted(names or REGISTRY))
        print(f"babblelint ok: {len(files)} files clean ({ran})")
    else:
        print(
            f"babblelint: {len(violations)} violation(s) — fix the site, "
            "or document it with '# lint: allow(<pass>: <reason>)'",
            file=sys.stderr,
        )
    return rc


if __name__ == "__main__":
    sys.exit(main())
