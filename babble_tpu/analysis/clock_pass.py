"""clock — the clock/RNG discipline pass.

PR 7's determinism contract (docs/simulation.md): every subsystem reads
time through ``Config.clock`` and derives randomness through
``Config.seeded_rng``, so a sim run is a pure function of the master
seed. A single bare ``time.time()`` or global-``random`` draw anywhere
in a node-side code path silently breaks byte-identical replay — the
exact class of bug this pass existed to catch (``control_timer.py``'s
gossip jitter and ``sentry.py``'s proof timestamps had both regressed
to the global sources before this pass landed).

What is flagged — *calls only*, never references:

- ``time.time/monotonic/sleep/perf_counter[_ns]/process_time(...)``
- module-level ``random.<draw>(...)`` (``random.Random(seed)`` and
  ``random.SystemRandom()`` construct *instances* and stay legal —
  seeded instances are exactly what the discipline asks for)
- ``datetime.now/utcnow/today(...)``

Injectable defaults like ``clock: Callable = time.monotonic`` are
references, not calls, and are the sanctioned shape for production
fallbacks — they stay clean by construction.

Deliberate wall-clock sites are declared, not tolerated: whole modules
whose business IS wall time are allowlisted below with a reason
(observability timestamps, device-stage timing, the wall-clock
abstraction itself), and scattered single sites carry
``# lint: allow(clock: <reason>)`` — which rots loudly (stale allows
are errors). The policy table lives in docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from .core import SourceFile, Violation, register

#: wall-time reads/sleeps on the ``time`` module
TIME_FNS = {
    "time",
    "monotonic",
    "sleep",
    "perf_counter",
    "time_ns",
    "monotonic_ns",
    "perf_counter_ns",
    "process_time",
}

#: ``random`` module attributes that are NOT global draws (constructing
#: a seeded instance is the sanctioned pattern)
RANDOM_CONSTRUCTORS = {"Random", "SystemRandom"}

DATETIME_FNS = {"now", "utcnow", "today"}

#: path prefix -> why the whole module is a sanctioned wall-clock site.
#: Kept small and each entry justified — the table is reproduced in
#: docs/static_analysis.md (wall-clock site policy).
MODULE_ALLOW: Dict[str, str] = {
    "babble_tpu/common/clock.py": "the wall-clock abstraction itself",
    "babble_tpu/obs/": (
        "observability timestamps are wall-clock by design (ledger/log/"
        "profiler/healthview stamps; stage clocks are injectable and "
        "telemetry wires them to the node clock)"
    ),
    "babble_tpu/sim/": (
        "the harness measures its own wall runtime; virtual time lives "
        "in SimClock"
    ),
    "babble_tpu/ops/": (
        "device-stage wall timing and device retry backoff; the "
        "accelerator path never runs under sim"
    ),
    "babble_tpu/hashgraph/accel.py": "device-stage wall timing (as ops/)",
    "babble_tpu/hashgraph/sweep_batcher.py": (
        "process-wide device dispatcher; COALESCE_S coalescing is real "
        "device-batching time and the accelerator is never enabled "
        "under sim (audited, docs/static_analysis.md)"
    ),
    "babble_tpu/net/signal.py": (
        "the relay transport is real-socket only; sim swaps in "
        "SimTransport"
    ),
    "babble_tpu/analysis/": "the lint suite is tooling, not node code",
}


def _module_allowed(path: str) -> bool:
    return any(path.startswith(p) for p in MODULE_ALLOW)


class _Imports(ast.NodeVisitor):
    """Local-name -> canonical module/function mapping for one file."""

    def __init__(self) -> None:
        self.module_alias: Dict[str, str] = {}  # local -> "time"/"random"/…
        self.from_names: Dict[str, Tuple[str, str]] = {}  # local -> (mod, fn)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name in ("time", "random", "datetime"):
                self.module_alias[a.asname or a.name] = a.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module in ("time", "random", "datetime"):
            for a in node.names:
                self.from_names[a.asname or a.name] = (node.module, a.name)


def _check_call(node: ast.Call, imp: _Imports) -> str:
    """Return a violation message for this call, or ''."""
    f = node.func
    # <alias>.<fn>(...)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        mod = imp.module_alias.get(f.value.id)
        if mod == "time" and f.attr in TIME_FNS:
            return (
                f"bare time.{f.attr}() — route through the node clock "
                "(Config.clock / common/clock.py WALL)"
            )
        if mod == "random" and f.attr not in RANDOM_CONSTRUCTORS:
            return (
                f"global random.{f.attr}() — draw from Config.seeded_rng "
                "(or an injected random.Random instance)"
            )
        if mod == "datetime" and f.attr in DATETIME_FNS:
            return (
                f"datetime.{f.attr}() — route through the node clock"
            )
        # datetime.datetime.now(...) via the module alias
        fn = imp.from_names.get(f.value.id)
        if fn == ("datetime", "datetime") and f.attr in DATETIME_FNS:
            return (
                f"datetime.{f.attr}() — route through the node clock"
            )
    # datetime.datetime.now(...) — two-level attribute off the module
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Attribute)
        and isinstance(f.value.value, ast.Name)
        and imp.module_alias.get(f.value.value.id) == "datetime"
        and f.attr in DATETIME_FNS
    ):
        return f"datetime.{f.attr}() — route through the node clock"
    # from time import sleep; sleep(...)
    if isinstance(f, ast.Name):
        origin = imp.from_names.get(f.id)
        if origin:
            mod, fn = origin
            if mod == "time" and fn in TIME_FNS:
                return (
                    f"bare {fn}() (from time import) — route through "
                    "the node clock"
                )
            if mod == "random" and fn not in RANDOM_CONSTRUCTORS:
                return (
                    f"global {fn}() (from random import) — draw from "
                    "Config.seeded_rng"
                )
            if mod == "datetime" and fn == "datetime":
                pass  # constructor itself is fine
    return ""


@register("clock")
def run(files: List[SourceFile], root: str) -> List[Violation]:
    out: List[Violation] = []
    for sf in files:
        if sf.tree is None or _module_allowed(sf.path):
            continue
        imp = _Imports()
        imp.visit(sf.tree)
        if not imp.module_alias and not imp.from_names:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                msg = _check_call(node, imp)
                if msg:
                    out.append(
                        Violation(sf.path, node.lineno, "clock", msg)
                    )
    return out
