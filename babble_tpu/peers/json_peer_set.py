"""JSONPeerSet — peers.json / peers.genesis.json loader
(reference: src/peers/json_peer_set.go:19)."""

from __future__ import annotations

import json
import os

from babble_tpu.peers.peer import Peer
from babble_tpu.peers.peer_set import PeerSet

PEERS_FILE = "peers.json"
GENESIS_PEERS_FILE = "peers.genesis.json"


class JSONPeerSet:
    def __init__(self, base_dir: str, genesis: bool = False):
        name = GENESIS_PEERS_FILE if genesis else PEERS_FILE
        self.path = os.path.join(base_dir, name)

    def peer_set(self) -> PeerSet:
        with open(self.path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        return PeerSet([Peer.from_dict(d) for d in raw])

    def write(self, ps: PeerSet) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "w", encoding="utf-8") as f:
            json.dump(ps.to_peer_slice(), f, indent=2)
