"""Peer — a validator identity (reference: src/peers/peer.go:13)."""

from __future__ import annotations

from dataclasses import dataclass, field

from babble_tpu.crypto.keys import PublicKey, public_key_id


@dataclass
class Peer:
    net_addr: str
    pub_key_hex: str
    moniker: str = ""
    _id: int = field(default=0, repr=False, compare=False)

    def __post_init__(self) -> None:
        # Normalize the pubkey hex to the canonical uppercase 0X form the
        # reference writes to peers.json (json_peer_set.go:62-77 cleansing).
        t = self.pub_key_hex
        if t[:2].upper() == "0X":
            t = t[2:]
        self.pub_key_hex = "0X" + t.upper()

    @property
    def id(self) -> int:
        """32-bit FNV-1a of the pubkey bytes (reference: peer.go:26-33)."""
        if self._id == 0:
            self._id = public_key_id(self.pub_key_bytes())
        return self._id

    def pub_key_bytes(self) -> bytes:
        return bytes.fromhex(self.pub_key_hex[2:])

    def public_key(self) -> PublicKey:
        return PublicKey.from_bytes(self.pub_key_bytes())

    def to_dict(self) -> dict:
        return {
            "NetAddr": self.net_addr,
            "PubKeyHex": self.pub_key_hex,
            "Moniker": self.moniker,
        }

    @staticmethod
    def from_dict(d: dict) -> "Peer":
        return Peer(
            net_addr=d.get("NetAddr", ""),
            pub_key_hex=d["PubKeyHex"],
            moniker=d.get("Moniker", ""),
        )
