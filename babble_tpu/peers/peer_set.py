"""PeerSet — an immutable validator set (reference: src/peers/peer_set.go).

Semantics that consensus depends on (must match the reference exactly):

- ``super_majority() = 2n/3 + 1`` (integer division, peer_set.go:157)
- ``trust_count()`` = 0 for n<=1, else ceil(n/3) (peer_set.go:165-177)
- ``hash()`` = iterated SimpleHashFromTwoHashes over the peers' pubkey bytes
  in set order — order-sensitive (peer_set.go:104-115)
- membership changes produce NEW PeerSets (with_new_peer / with_removed_peer,
  peer_set.go:46-69); the engine records one PeerSet per round.

Peers are kept sorted by pubkey hex, which fixes the iteration order used by
the hash and by tensor layouts in the TPU kernels (peer index = position in
this sorted order).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Optional

from babble_tpu.crypto.hashing import simple_hash_from_two_hashes
from babble_tpu.peers.peer import Peer


class PeerSet:
    def __init__(self, peers: Iterable[Peer]):
        self.peers: List[Peer] = sorted(peers, key=lambda p: p.pub_key_hex)
        self.by_pub_key: Dict[str, Peer] = {p.pub_key_hex: p for p in self.peers}
        self.by_id: Dict[int, Peer] = {p.id: p for p in self.peers}
        self._hash: Optional[bytes] = None

    def __len__(self) -> int:
        return len(self.peers)

    def __contains__(self, pub_key_hex: str) -> bool:
        return pub_key_hex in self.by_pub_key

    def ids(self) -> List[int]:
        return [p.id for p in self.peers]

    def pub_keys(self) -> List[str]:
        return [p.pub_key_hex for p in self.peers]

    def peer_index(self, pub_key_hex: str) -> int:
        """Dense index of a peer in sorted order — the tensor coordinate used
        by the JAX kernels (lastAncestors[:, peer_index] etc.)."""
        for i, p in enumerate(self.peers):
            if p.pub_key_hex == pub_key_hex:
                return i
        raise KeyError(pub_key_hex)

    def with_new_peer(self, peer: Peer) -> "PeerSet":
        if peer.pub_key_hex in self.by_pub_key:
            return PeerSet(list(self.peers))
        return PeerSet(list(self.peers) + [peer])

    def with_removed_peer(self, peer: Peer) -> "PeerSet":
        return self.with_removed_pub_key(peer.pub_key_hex)

    def with_removed_pub_key(self, pub_key_hex: str) -> "PeerSet":
        return PeerSet([p for p in self.peers if p.pub_key_hex != pub_key_hex])

    def super_majority(self) -> int:
        """Strictly more than 2/3: 2n/3 + 1 (reference: peer_set.go:157)."""
        return 2 * len(self.peers) // 3 + 1

    def trust_count(self) -> int:
        """Minimum signature count representing finality: 0 for sets of one
        or fewer peers, ceil(n/3) otherwise (reference: peer_set.go:165-177)."""
        if len(self.peers) <= 1:
            return 0
        return int(math.ceil(len(self.peers) / 3))

    def hash(self) -> bytes:
        if self._hash is None:
            h = b""
            for p in self.peers:
                h = simple_hash_from_two_hashes(h, p.pub_key_bytes())
            self._hash = h
        return self._hash

    def to_peer_slice(self) -> List[dict]:
        return [p.to_dict() for p in self.peers]

    @staticmethod
    def from_peer_slice(items: List[dict]) -> "PeerSet":
        return PeerSet([Peer.from_dict(d) for d in items])

    def __eq__(self, other: object) -> bool:
        return isinstance(other, PeerSet) and self.pub_keys() == other.pub_keys()

    def __repr__(self) -> str:
        return f"PeerSet({[p.moniker or p.pub_key_hex[:10] for p in self.peers]})"
