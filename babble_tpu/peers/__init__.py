"""Peers / validator sets (reference: src/peers/)."""

from babble_tpu.peers.peer import Peer
from babble_tpu.peers.peer_set import PeerSet
from babble_tpu.peers.json_peer_set import JSONPeerSet

__all__ = ["JSONPeerSet", "Peer", "PeerSet"]
