"""babble_tpu — a TPU-native BFT consensus framework.

A brand-new implementation of leaderless Byzantine-fault-tolerant transaction
ordering via Hashgraph virtual voting (Baird 2016), with the capability
surface of the reference Go implementation (sikoba/babble):

- gossip-about-gossip networking (in-memory / TCP transports),
- a blockchain projection with signed blocks,
- dynamic validator membership (join/leave through consensus),
- fast-sync from frame checkpoints and app snapshots,
- a language-agnostic app proxy (in-memory and socket),
- an HTTP observability service and a CLI.

Unlike the pure-Go reference, the per-event compute — batched secp256k1
signature verification and the DAG round/fame/ordering pipeline — is
re-expressed as JAX/XLA kernels (see `babble_tpu.ops`), sharded over TPU
meshes with `shard_map` (see `babble_tpu.parallel`). The gossip layer is the
DCN control plane feeding the TPU as a consensus coprocessor.

Reference layer map: SURVEY.md §1; component inventory: SURVEY.md §2.
"""

from babble_tpu.version import __version__

__all__ = ["__version__"]
