"""Trilean — three-valued logic for undecided votes (reference: src/common/trilean.go)."""

from __future__ import annotations

import enum


class Trilean(enum.IntEnum):
    UNDEFINED = 0
    TRUE = 1
    FALSE = 2

    def __str__(self) -> str:
        return {0: "Undefined", 1: "True", 2: "False"}[int(self)]
