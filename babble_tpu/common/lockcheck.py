"""Runtime lock-order recorder — the empirical half of the babblelint
lock-discipline pass (docs/static_analysis.md §Lock model).

The static pass (``analysis/lock_pass.py``) derives the acquisition-
order graph from ``with`` statements and a name-based call graph; this
recorder observes the REAL graph: every named :class:`TimedLock`
acquire/release reports here when ``BABBLE_LOCKCHECK=1``, and acquiring
lock B while holding lock A records the directed edge A→B with the
held-stack witness. An *inversion* — both A→B and B→A observed — is a
latent deadlock the static model either missed (callback, dynamic
dispatch) or proved; either way CI fails on it: the chaos soak and the
sim sweep both run with the recorder armed and assert zero inversions.

Disabled (the default), the hook is one module-attribute truth test per
acquire — nothing is allocated, no thread-local is touched. The
recorder is process-wide: co-located nodes share it, which is exactly
right — their threads share the actual locks' deadlock potential too.

Surfaced as ``lock_order_edges`` / ``lock_order_inversions`` in
``get_stats`` (node/node.py) and in the sim sweep summary line.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Tuple

#: armed at import from the environment; tests flip it via set_enabled()
ENABLED: bool = os.environ.get("BABBLE_LOCKCHECK", "") not in (
    "", "0", "false", "off", "no",
)


def set_enabled(on: bool) -> None:
    """Test hook — production arming is the BABBLE_LOCKCHECK env var."""
    global ENABLED
    ENABLED = bool(on)


class LockOrderRecorder:
    """Per-thread held-lock stacks + the process-wide edge set."""

    def __init__(self) -> None:
        self._tls = threading.local()
        self._lock = threading.Lock()
        # (held, acquired) -> times observed; first-witness stack kept
        self.edges: Dict[Tuple[str, str], int] = {}
        self.witness: Dict[Tuple[str, str], str] = {}

    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquired(self, name: str) -> None:
        st = self._stack()
        if st:
            with self._lock:
                for held in st:
                    if held != name:
                        key = (held, name)
                        self.edges[key] = self.edges.get(key, 0) + 1
                        self.witness.setdefault(key, "<".join(st))
        st.append(name)

    def note_released(self, name: str) -> None:
        st = self._stack()
        # release order may not mirror acquire order; drop the latest
        # matching entry
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break

    def edge_list(self) -> List[str]:
        with self._lock:
            return sorted(f"{a}->{b}" for (a, b) in self.edges)

    def inversions(self) -> List[str]:
        """Lock pairs observed in BOTH orders — each is a latent
        deadlock between the two acquisition sites."""
        with self._lock:
            out = []
            for (a, b) in self.edges:
                if (b, a) in self.edges and a < b:
                    out.append(
                        f"{a}<->{b} (held {self.witness[(a, b)]} then "
                        f"{b}; held {self.witness[(b, a)]} then {a})"
                    )
            return sorted(out)

    def stats(self) -> dict:
        return {
            "lock_order_edges": self.edge_list(),
            "lock_order_inversions": len(self.inversions()),
        }

    def reset(self) -> None:
        with self._lock:
            self.edges.clear()
            self.witness.clear()


#: the process-wide recorder every named TimedLock reports to
RECORDER = LockOrderRecorder()
