"""Latency recorder for the gossip hot path.

The reference logs nanosecond durations around requestSync / Diff / Sync /
ProcessSigPool on every gossip round (src/node/node.go:511-514,543-548,
593-608) and exposes profiling via pprof on the service mux
(cmd/babble/main.go:4). Here the same measurements are aggregated into
bounded per-name reservoirs and served at /debug/timers.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict


class LatencyRecorder:
    def __init__(self, window: int = 512):
        self._window = window
        self._lock = threading.Lock()
        self._samples: Dict[str, deque] = {}
        self._counts: Dict[str, int] = {}
        self._totals: Dict[str, float] = {}

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            d = self._samples.get(name)
            if d is None:
                d = self._samples[name] = deque(maxlen=self._window)
                self._counts[name] = 0
                self._totals[name] = 0.0
            d.append(seconds)
            self._counts[name] += 1
            self._totals[name] += seconds

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, d in self._samples.items():
                vals = sorted(d)
                n = len(vals)
                if n == 0:
                    continue
                out[name] = {
                    "count": self._counts[name],
                    "total_ms": round(self._totals[name] * 1e3, 3),
                    "mean_ms": round(sum(vals) / n * 1e3, 3),
                    "p50_ms": round(vals[n // 2] * 1e3, 3),
                    "p95_ms": round(vals[min(n - 1, int(n * 0.95))] * 1e3, 3),
                    "max_ms": round(vals[-1] * 1e3, 3),
                }
        return out
