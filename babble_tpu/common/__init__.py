"""Common utilities (reference: src/common/).

LRU cache, rolling indexes, typed store errors, trilean logic, median.
"""

from babble_tpu.common.errors import (
    StoreError,
    StoreErrorKind,
    is_store_err,
)
from babble_tpu.common.lru import LRU
from babble_tpu.common.rolling_index import RollingIndex
from babble_tpu.common.rolling_index_map import RollingIndexMap
from babble_tpu.common.trilean import Trilean
from babble_tpu.common.utils import median_int

__all__ = [
    "LRU",
    "RollingIndex",
    "RollingIndexMap",
    "StoreError",
    "StoreErrorKind",
    "Trilean",
    "is_store_err",
    "median_int",
]
