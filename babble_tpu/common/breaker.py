"""Circuit breaker: stop hammering a failing dependency, probe it back.

Classic three-state machine (closed → open → half-open → closed):

- **closed**: calls flow. Failures are timestamped; when ``threshold``
  failures land within ``window_s``, the breaker opens.
- **open**: calls are refused (``allow()`` is False) for ``cooldown_s``,
  so a dying dependency isn't paid for on every call.
- **half-open**: after the cooldown, exactly ONE probe call is admitted.
  Success closes the breaker (failure history cleared); failure re-opens
  it for another cooldown.

Used by hashgraph/accel.py to gate the device sweep path: a flapping
accelerator (tunnel resets, OOMs) degrades to the oracle for a cooldown
instead of eating a dispatch failure per flush, and — unlike a sticky
kill-switch — the probe sweep re-enables the device once it recovers.

``clock`` is injectable so tests drive the state machine without
sleeping. Thread-safe: gossip threads and the readback reader may race
record_* against allow().
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(
        self,
        threshold: int = 5,
        window_s: float = 30.0,
        cooldown_s: float = 15.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = threshold
        self.window_s = window_s
        self.cooldown_s = cooldown_s
        # Accept either a bare monotonic callable or a common.clock.Clock
        # object (the node hands its Clock through, so simulated breakers
        # trip and cool down on virtual time).
        self._clock = getattr(clock, "monotonic", clock)
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures: List[float] = []  # timestamps inside the window
        self._opened_at = 0.0
        self._probe_out = False  # half-open: one probe admitted at a time
        # counters surfaced through stats()
        self.opens = 0  # closed/half-open → open transitions
        self.probes = 0  # probe calls admitted while half-open
        self.skips = 0  # calls refused while open
        self.failures_total = 0
        self.successes_total = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """True when a call may proceed. While open, flips to half-open
        once the cooldown elapses and admits a single probe."""
        now = self._clock()
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at < self.cooldown_s:
                    self.skips += 1
                    return False
                self._state = HALF_OPEN
                self._probe_out = False
            # half-open: admit one probe; refuse the rest until it reports
            if self._probe_out:
                self.skips += 1
                return False
            self._probe_out = True
            self.probes += 1
            return True

    def cancel(self) -> None:
        """The admitted call never actually reached the dependency (e.g.
        kernels still compiling, admission slot lost): release the probe
        without treating it as an outcome."""
        with self._lock:
            self._probe_out = False

    def record_success(self) -> None:
        with self._lock:
            self.successes_total += 1
            if self._state == OPEN:
                # late success from a call admitted before the trip (e.g.
                # an in-flight readback landing after the Nth failure):
                # the cooldown still stands — only a half-open probe may
                # re-close the breaker
                return
            self._failures.clear()
            self._probe_out = False
            self._state = CLOSED

    def record_failure(self) -> None:
        now = self._clock()
        with self._lock:
            self.failures_total += 1
            if self._state == HALF_OPEN:
                # the probe failed: straight back to open, fresh cooldown
                self._open(now)
                return
            if self._state == OPEN:
                # late failure from a call admitted before the open (e.g.
                # an in-flight readback landing after the breaker tripped)
                return
            self._failures.append(now)
            cutoff = now - self.window_s
            self._failures = [t for t in self._failures if t >= cutoff]
            if len(self._failures) >= self.threshold:
                self._open(now)

    def _open(self, now: float) -> None:
        self._state = OPEN
        self._opened_at = now
        self._failures.clear()
        self._probe_out = False
        self.opens += 1

    def stats(self, prefix: str = "breaker_") -> dict:
        with self._lock:
            return {
                f"{prefix}state": self._state,
                f"{prefix}open": self.opens,
                f"{prefix}probes": self.probes,
                f"{prefix}skips": self.skips,
                f"{prefix}failures": self.failures_total,
            }
