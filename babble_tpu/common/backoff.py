"""Jittered exponential backoff, shared by every retry site.

One formula so the selector's per-peer backoff, the joining node's retry
sleep, and the fast-forward poll loop all behave identically:

    delay = min(cap_s, base_s * 2^(attempt-1) * (1 + jitter * u))

with u drawn uniform from [-1, 1]. Jitter multiplies BEFORE the cap, so
``cap_s`` is a hard bound — a configured 2 s cap never sleeps 2.5 s.
"""

from __future__ import annotations

import random
from typing import Optional


def jittered_backoff(
    attempt: int,
    base_s: float,
    cap_s: float,
    jitter: float = 0.25,
    rng: Optional[random.Random] = None,
) -> float:
    """Backoff for the ``attempt``-th consecutive failure (1-based).
    The exponent is clamped: a permanently dead peer accrues failures
    forever, and an unclamped 2**n overflows float after ~1000 of them
    (the cap has long since dominated anyway)."""
    if attempt < 1:
        return 0.0
    u = (rng.uniform(-1.0, 1.0) if rng is not None
         # lint: allow(clock: production fallback; sim callers always inject a seeded rng)
         else random.uniform(-1.0, 1.0))
    nominal = base_s * (2.0 ** min(attempt - 1, 32))
    return min(cap_s, nominal * (1.0 + jitter * u))


def backoff_sleep(
    attempt: int,
    base_s: float,
    cap_s: float,
    jitter: float = 0.25,
    rng: Optional[random.Random] = None,
    sleep=None,
) -> float:
    """Compute the jittered delay AND wait it out through an injectable
    ``sleep`` (default: the process wall clock). Retry sites pass their
    node's ``Clock.sleep`` so a simulated cluster's backoff waits are
    virtual — a joining node's 2 s retry cadence costs the sim engine
    nothing but a clock advance. Returns the delay actually slept."""
    delay = jittered_backoff(attempt, base_s, cap_s, jitter, rng)
    if delay > 0.0:
        if sleep is None:
            import time

            sleep = time.sleep
        sleep(delay)
    return delay
