"""Typed store errors (reference: src/common/store_errors.go:8-41).

The consensus engine distinguishes *why* a lookup failed: a key that was
never set (KEY_NOT_FOUND) is handled differently from one that was evicted
from a rolling window (TOO_LATE) or an out-of-order append (SKIPPED_INDEX).
"""

from __future__ import annotations

import enum


class StoreErrorKind(enum.Enum):
    KEY_NOT_FOUND = "not found"
    TOO_LATE = "too late"
    SKIPPED_INDEX = "skipped index"
    UNKNOWN_PARTICIPANT = "unknown participant"
    EMPTY = "empty"
    KEY_ALREADY_EXISTS = "key already exists"
    # Write refused because the store is closed (shutdown race). Consensus
    # objects must be durable before they become visible to gossip, so a
    # closed store FAILS writes instead of dropping them (the drop let a
    # node gossip an event, lose it at close, and fork itself after
    # bootstrap).
    CLOSED = "store closed"


class StoreError(Exception):
    """Error with a typed kind, so callers can branch on the failure mode."""

    def __init__(self, resource: str, kind: StoreErrorKind, key: str = ""):
        self.resource = resource
        self.kind = kind
        self.key = key
        super().__init__(f"{resource}, {key}, {kind.value}")


def is_store_err(err: object, kind: StoreErrorKind) -> bool:
    """True iff err is a StoreError of the given kind (reference: store_errors.go:36-41)."""
    return isinstance(err, StoreError) and err.kind == kind
