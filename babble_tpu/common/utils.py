"""Small shared helpers (reference: src/common/median.go, hex codecs)."""

from __future__ import annotations

from typing import Sequence


def median_int(values: Sequence[int]) -> int:
    """Median of integers; even-length lists take the lower-middle element,
    matching the reference's sort-and-index-n/2 behavior on timestamp lists
    (reference: src/common/median.go:8, used by hashgraph.go:1264-1273)."""
    if not values:
        raise ValueError("median of empty sequence")
    s = sorted(values)
    return s[len(s) // 2]
