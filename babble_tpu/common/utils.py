"""Small shared helpers (reference: src/common/median.go, hex codecs)."""

from __future__ import annotations

from typing import Sequence


def median_int(values: Sequence[int]) -> int:
    """Median of integers, matching the reference exactly: empty input yields
    0; even-length lists average the two middle values with truncating
    (toward-zero) integer division, as Go's int64 division does; odd-length
    lists take the middle element (reference: src/common/median.go:8-29,
    used for BFT frame timestamps at hashgraph.go:1264-1273)."""
    s = sorted(values)
    n = len(s)
    if n == 0:
        return 0
    if n % 2 == 0:
        mid = n // 2 - 1
        total = s[mid] + s[mid + 1]
        # Go integer division truncates toward zero; Python's // floors.
        return total // 2 if total >= 0 else -((-total) // 2)
    return s[n // 2]
