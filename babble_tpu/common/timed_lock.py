"""TimedLock — a threading.Lock that accounts its acquisition wait time.

The node serializes all hashgraph access on one core lock (the reference's
coreLock discipline, node.go:35); the round-5 profile put ~70% of
co-located samples inside ``lock.acquire``. Shrinking those critical
sections is only credible if the wait is *measured*, so the node's core
lock is this instrumented wrapper and ``get_stats`` surfaces
``lock_wait_ms_total`` / ``lock_acquisitions`` from it.

Accounting is monotonic-clock wall time summed across every acquiring
thread; under the GIL the float += races are benign for a stats counter
(worst case an update is lost, never corrupted).

A ``name`` makes the lock part of the acquisition-order model: when the
``BABBLE_LOCKCHECK=1`` recorder (common/lockcheck.py) is armed, named
acquires/releases feed the process-wide order graph that validates the
babblelint static lock pass (docs/static_analysis.md §Lock model). The
other consensus-path locks (mempool, sentry, subscription hub) are
named TimedLocks too for exactly this reason. Disabled, the hook costs
one module-attribute truth test on the acquire fast path.
"""

from __future__ import annotations

import threading
import time

from . import lockcheck


class TimedLock:
    """Drop-in ``threading.Lock`` replacement that records total time
    spent *waiting* to acquire (contention, not hold time)."""

    __slots__ = (
        "_lock", "wait_s_total", "acquisitions", "observer", "_clock", "name",
    )

    def __init__(self, observer=None, clock=time.perf_counter,
                 name=None) -> None:
        self._lock = threading.Lock()
        self.wait_s_total: float = 0.0
        self.acquisitions: int = 0
        # Optional per-contended-acquire wait observer (seconds) — the
        # node wires the core_lock_wait_seconds histogram here; only
        # contended acquires are observed (the fast path stays clockless).
        self.observer = observer
        # Injectable so simulated nodes account waits in virtual time.
        self._clock = clock
        # Named locks participate in the BABBLE_LOCKCHECK order recorder.
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Fast path: an uncontended acquire skips the two clock reads —
        # this wrapper must not tax the very path it instruments.
        if self._lock.acquire(False):
            self.acquisitions += 1
            if lockcheck.ENABLED and self.name:
                lockcheck.RECORDER.note_acquired(self.name)
            return True
        if not blocking:
            return False
        t0 = self._clock()
        ok = self._lock.acquire(True, timeout)
        waited = self._clock() - t0
        self.wait_s_total += waited
        if self.observer is not None:
            self.observer(waited)
        if ok:
            self.acquisitions += 1
            if lockcheck.ENABLED and self.name:
                lockcheck.RECORDER.note_acquired(self.name)
        return ok

    def release(self) -> None:
        self._lock.release()
        if lockcheck.ENABLED and self.name:
            lockcheck.RECORDER.note_released(self.name)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait_ms_total(self) -> float:
        return 1e3 * self.wait_s_total


def named_lock(name: str):
    """A lock that participates in the BABBLE_LOCKCHECK order recorder —
    as a named TimedLock when the recorder is armed, and a raw C
    ``threading.Lock`` otherwise: the mempool/sentry/pipeline/batcher
    hot paths must not pay a Python-level acquire wrapper to feed a
    default-off debug recorder (the core lock stays a TimedLock always:
    its wait accounting IS a production stat). Arming is decided at
    construction, matching the env-var contract — tests that flip
    ``lockcheck.set_enabled`` do so before building their cluster."""
    if lockcheck.ENABLED:
        return TimedLock(name=name)
    return threading.Lock()
