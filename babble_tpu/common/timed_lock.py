"""TimedLock — a threading.Lock that accounts its acquisition wait time.

The node serializes all hashgraph access on one core lock (the reference's
coreLock discipline, node.go:35); the round-5 profile put ~70% of
co-located samples inside ``lock.acquire``. Shrinking those critical
sections is only credible if the wait is *measured*, so the node's core
lock is this instrumented wrapper and ``get_stats`` surfaces
``lock_wait_ms_total`` / ``lock_acquisitions`` from it.

Accounting is monotonic-clock wall time summed across every acquiring
thread; under the GIL the float += races are benign for a stats counter
(worst case an update is lost, never corrupted).
"""

from __future__ import annotations

import threading
import time


class TimedLock:
    """Drop-in ``threading.Lock`` replacement that records total time
    spent *waiting* to acquire (contention, not hold time)."""

    __slots__ = ("_lock", "wait_s_total", "acquisitions", "observer", "_clock")

    def __init__(self, observer=None, clock=time.perf_counter) -> None:
        self._lock = threading.Lock()
        self.wait_s_total: float = 0.0
        self.acquisitions: int = 0
        # Optional per-contended-acquire wait observer (seconds) — the
        # node wires the core_lock_wait_seconds histogram here; only
        # contended acquires are observed (the fast path stays clockless).
        self.observer = observer
        # Injectable so simulated nodes account waits in virtual time.
        self._clock = clock

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Fast path: an uncontended acquire skips the two clock reads —
        # this wrapper must not tax the very path it instruments.
        if self._lock.acquire(False):
            self.acquisitions += 1
            return True
        if not blocking:
            return False
        t0 = self._clock()
        ok = self._lock.acquire(True, timeout)
        waited = self._clock() - t0
        self.wait_s_total += waited
        if self.observer is not None:
            self.observer(waited)
        if ok:
            self.acquisitions += 1
        return ok

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait_ms_total(self) -> float:
        return 1e3 * self.wait_s_total
