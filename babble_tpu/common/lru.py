"""LRU cache (reference: src/common/lru.go).

A small, deterministic LRU with an optional eviction callback. Backed by an
OrderedDict; most-recently-used entries live at the end.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Iterator, Optional


class LRU:
    def __init__(self, size: int, evict_callback: Optional[Callable[[Any, Any], None]] = None):
        if size <= 0:
            raise ValueError("LRU size must be positive")
        self.size = size
        self._evict = evict_callback
        self._items: OrderedDict[Any, Any] = OrderedDict()

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, key: Any) -> bool:
        return key in self._items

    _MISS = object()

    def get(self, key: Any) -> tuple[Any, bool]:
        """Return (value, ok); refreshes recency on hit."""
        # single lookup instead of contains+move+getitem: this runs tens
        # of times per event insert across the six hashgraph caches
        val = self._items.get(key, LRU._MISS)
        if val is LRU._MISS:
            return None, False
        self._items.move_to_end(key)
        return val, True

    def add(self, key: Any, value: Any) -> bool:
        """Insert/update; returns True if an eviction occurred."""
        if key in self._items:
            self._items.move_to_end(key)
            self._items[key] = value
            return False
        self._items[key] = value
        if len(self._items) > self.size:
            old_key, old_val = self._items.popitem(last=False)
            if self._evict is not None:
                self._evict(old_key, old_val)
            return True
        return False

    def peek(self, key: Any) -> tuple[Any, bool]:
        """Like get, without refreshing recency."""
        if key not in self._items:
            return None, False
        return self._items[key], True

    def remove(self, key: Any) -> bool:
        if key in self._items:
            del self._items[key]
            return True
        return False

    def keys(self) -> Iterator[Any]:
        """Keys oldest → newest."""
        return iter(list(self._items.keys()))

    def purge(self) -> None:
        if self._evict is not None:
            for k, v in self._items.items():
                self._evict(k, v)
        self._items.clear()
