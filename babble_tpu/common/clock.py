"""The time abstraction every subsystem reads through.

One ``Clock`` object carries the four time primitives the framework
uses — ``monotonic()`` (scheduling, deadlines, backoff), ``time()``
(wall timestamps stamped into event bodies and evidence records),
``perf_counter()`` (duration measurement for telemetry), and
``sleep()`` (every blocking wait). Production code gets ``WALL``, a
process-wide singleton delegating to the ``time`` module; the
deterministic simulation engine (``babble_tpu.sim``) injects a
``SimClock`` whose time is virtual, so a 10-second soak collapses to
milliseconds and every duration the telemetry records is a pure
function of the schedule, not of host load.

Subsystems that predate this class take bare callables
(``clock=time.monotonic`` — breaker, selector, mempool, sentry);
those keep their callable signature and are handed the bound method
(``conf.clock.monotonic``) by their constructors. New code should
take the ``Clock`` object so it can reach all four primitives.
"""

from __future__ import annotations

import time


class Clock:
    """Interface: see module docstring. Subclasses override all four."""

    def monotonic(self) -> float:
        raise NotImplementedError

    def perf_counter(self) -> float:
        # one high-resolution timeline is enough for both scheduling and
        # duration measurement unless a subclass says otherwise
        return self.monotonic()

    def time(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class WallClock(Clock):
    """The real thing. Stateless; use the ``WALL`` singleton."""

    def monotonic(self) -> float:
        return time.monotonic()

    def perf_counter(self) -> float:
        return time.perf_counter()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


WALL = WallClock()
