"""RollingIndex — bounded FIFO with strict sequential indexes.

Reference: src/common/rolling_index.go:8-110. Items are appended at
consecutive integer indexes; when the buffer holds `size` items, the next
append first evicts the oldest half (keeping items[size//2:]). Reads below
the retained window raise TOO_LATE; reads beyond the head raise
KEY_NOT_FOUND; non-sequential appends raise SKIPPED_INDEX.

Concurrency: writes are serialized by the owner (the node's core-lock
discipline), but the batched-ingest fast path READS participant indexes
without that lock (Core.prepare_sync). Readers therefore resolve against
``_window`` — an immutable (items, last_index, count) tuple the writer
publishes atomically after every append — so a read can never mix a new
``_last_index`` with an old item list (the torn read resolved the WRONG
parent for an in-flight decode). The tuple's ``count`` pins the mapping
even while the shared list grows underneath it; ``_roll`` swaps in a new
list, leaving published snapshots self-consistent.
"""

from __future__ import annotations

from typing import Any, List

from babble_tpu.common.errors import StoreError, StoreErrorKind


class RollingIndex:
    def __init__(self, name: str, size: int):
        self.name = name
        self.size = size
        self._items: List[Any] = []
        self._last_index = -1
        # (items, last_index, count) — atomically replaced on append
        self._window: tuple = (self._items, -1, 0)

    def get_last_window(self) -> tuple[list[Any], int]:
        items, last, n = self._window
        return items[:n], last

    def last_index(self) -> int:
        """Head index without copying the window (known-events maps read
        this per participant per gossip round)."""
        return self._window[1]

    def last_item(self) -> Any:
        """Newest item, or None when empty — again without the copy."""
        items, _, n = self._window
        return items[n - 1] if n else None

    def get(self, skip_index: int) -> list[Any]:
        """Return items with index > skip_index (reference: rolling_index.go:33-55)."""
        items, last, n = self._window
        if skip_index > last:
            return []
        cached_start = last - n + 1
        if skip_index + 1 < cached_start:
            raise StoreError(self.name, StoreErrorKind.TOO_LATE, str(skip_index))
        start = skip_index + 1 - cached_start
        return items[start:n]

    def get_item(self, index: int) -> Any:
        items, last, n = self._window
        cached_start = last - n + 1
        if index < cached_start:
            raise StoreError(self.name, StoreErrorKind.TOO_LATE, str(index))
        if index > last:
            raise StoreError(self.name, StoreErrorKind.KEY_NOT_FOUND, str(index))
        return items[index - cached_start]

    def set(self, item: Any, index: int) -> None:
        # Updating a stored item in place is allowed (reference:
        # rolling_index.go:78-84); the mapping is unchanged, so published
        # snapshots stay valid.
        if self._items and index <= self._last_index:
            cached_start = self._last_index - len(self._items) + 1
            if index < cached_start:
                raise StoreError(self.name, StoreErrorKind.TOO_LATE, str(index))
            self._items[index - cached_start] = item
            return
        if self._last_index >= 0 and index > self._last_index + 1:
            raise StoreError(self.name, StoreErrorKind.SKIPPED_INDEX, str(index))
        if len(self._items) >= self.size:
            self._roll()
        self._items.append(item)
        self._last_index = index
        self._window = (self._items, index, len(self._items))

    def _roll(self) -> None:
        # Evict the earlier half, keeping items[size//2:] (rolling_index.go:105-109).
        # A NEW list: snapshots published before the roll keep indexing
        # the old one consistently.
        self._items = self._items[self.size // 2 :]
