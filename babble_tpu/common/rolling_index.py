"""RollingIndex — bounded FIFO with strict sequential indexes.

Reference: src/common/rolling_index.go:8-110. Items are appended at
consecutive integer indexes; when the buffer holds `size` items, the next
append first evicts the oldest half (keeping items[size//2:]). Reads below
the retained window raise TOO_LATE; reads beyond the head raise
KEY_NOT_FOUND; non-sequential appends raise SKIPPED_INDEX.
"""

from __future__ import annotations

from typing import Any, List

from babble_tpu.common.errors import StoreError, StoreErrorKind


class RollingIndex:
    def __init__(self, name: str, size: int):
        self.name = name
        self.size = size
        self._items: List[Any] = []
        self._last_index = -1

    def get_last_window(self) -> tuple[list[Any], int]:
        return self._items, self._last_index

    def get(self, skip_index: int) -> list[Any]:
        """Return items with index > skip_index (reference: rolling_index.go:33-55)."""
        if skip_index > self._last_index:
            return []
        cached_start = self._last_index - len(self._items) + 1
        if skip_index + 1 < cached_start:
            raise StoreError(self.name, StoreErrorKind.TOO_LATE, str(skip_index))
        start = skip_index + 1 - cached_start
        return self._items[start:]

    def get_item(self, index: int) -> Any:
        n = len(self._items)
        cached_start = self._last_index - n + 1
        if index < cached_start:
            raise StoreError(self.name, StoreErrorKind.TOO_LATE, str(index))
        if index > self._last_index:
            raise StoreError(self.name, StoreErrorKind.KEY_NOT_FOUND, str(index))
        return self._items[index - cached_start]

    def set(self, item: Any, index: int) -> None:
        # Updating a stored item in place is allowed (reference: rolling_index.go:78-84).
        if self._items and index <= self._last_index:
            cached_start = self._last_index - len(self._items) + 1
            if index < cached_start:
                raise StoreError(self.name, StoreErrorKind.TOO_LATE, str(index))
            self._items[index - cached_start] = item
            return
        if self._last_index >= 0 and index > self._last_index + 1:
            raise StoreError(self.name, StoreErrorKind.SKIPPED_INDEX, str(index))
        if len(self._items) >= self.size:
            self._roll()
        self._items.append(item)
        self._last_index = index

    def _roll(self) -> None:
        # Evict the earlier half, keeping items[size//2:] (rolling_index.go:105-109).
        self._items = self._items[self.size // 2 :]
