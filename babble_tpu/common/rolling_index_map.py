"""RollingIndexMap — per-participant rolling indexes.

Reference: src/common/rolling_index_map.go. Keys are uint32 participant IDs;
each maps to an independent RollingIndex of the same size.
"""

from __future__ import annotations

from typing import Any, Dict, List

from babble_tpu.common.errors import StoreError, StoreErrorKind
from babble_tpu.common.rolling_index import RollingIndex


class RollingIndexMap:
    def __init__(self, name: str, size: int, keys: list[int] | None = None):
        self.name = name
        self.size = size
        self.keys: List[int] = []
        self.mapping: Dict[int, RollingIndex] = {}
        for k in keys or []:
            self.add_key(k)

    def add_key(self, key: int) -> None:
        if key in self.mapping:
            raise StoreError(self.name, StoreErrorKind.KEY_ALREADY_EXISTS, str(key))
        self.keys.append(key)
        self.mapping[key] = RollingIndex(f"{self.name}[{key}]", self.size)

    def get(self, key: int, skip_index: int) -> list[Any]:
        if key not in self.mapping:
            raise StoreError(self.name, StoreErrorKind.KEY_NOT_FOUND, str(key))
        return self.mapping[key].get(skip_index)

    def get_item(self, key: int, index: int) -> Any:
        if key not in self.mapping:
            raise StoreError(self.name, StoreErrorKind.KEY_NOT_FOUND, str(key))
        return self.mapping[key].get_item(index)

    def get_last(self, key: int) -> Any:
        if key not in self.mapping:
            raise StoreError(self.name, StoreErrorKind.KEY_NOT_FOUND, str(key))
        item = self.mapping[key].last_item()
        if item is None:
            raise StoreError(self.name, StoreErrorKind.EMPTY, str(key))
        return item

    def set(self, key: int, item: Any, index: int) -> None:
        if key not in self.mapping:
            self.add_key(key)
        self.mapping[key].set(item, index)

    def known(self) -> dict[int, int]:
        """Map key → last known index (reference: rolling_index_map.go:85-97).
        Reads only the head index — copying each participant's whole
        window here would put O(cache_size) allocations inside the very
        critical section the ingest fast path shrinks."""
        return {k: ri.last_index() for k, ri in self.mapping.items()}
