// babble_tpu native batch crypto: self-contained secp256k1 ECDSA + SHA-256.
//
// This is the framework's native runtime component for host-side signature
// work: the gossip hot path verifies every incoming event's signature
// (reference: src/hashgraph/event.go:219-247 via hashgraph.go:672-687) and
// signs every self-event (src/node/core.go:337-343). The batch C ABI lets
// Python hand a whole sync's worth of (pubkey, hash, signature) tuples over
// in ONE call, avoiding per-op FFI overhead.
//
// Semantics mirror babble_tpu/crypto/secp256k1.py exactly (differentially
// tested): RFC 6979 deterministic nonces, NO low-s normalization (matching
// Go's crypto/ecdsa which the reference uses, keys/signature.go:13-18),
// e = leftmost 256 bits of the hash, r/s in [1, n-1], pubkey must satisfy
// the curve equation mod p.
//
// Implementation: 4x64-bit limbs with unsigned __int128 accumulation;
// reduction exploits p = 2^256 - 0x1000003D1 and 2^256 mod n folding;
// Jacobian coordinates (a=0 doubling), Strauss-Shamir interleaved 4-bit
// windows for u1*G + u2*Q with a precomputed affine G table.
//
// Build: g++ -O3 -shared -fPIC -pthread -o libbabble_crypto.so secp256k1.cc

#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

typedef uint64_t u64;
typedef unsigned __int128 u128;
typedef uint32_t u32;
typedef uint8_t u8;

// ---------------------------------------------------------------------------
// SHA-256 (for RFC 6979 HMAC and the sign-loop rehash)
// ---------------------------------------------------------------------------

static const u32 SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

struct Sha256 {
    u32 h[8];
    u8 buf[64];
    u64 len;
    int buflen;

    void init() {
        static const u32 H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                  0xa54ff53a, 0x510e527f, 0x9b05688c,
                                  0x1f83d9ab, 0x5be0cd19};
        memcpy(h, H0, sizeof(h));
        len = 0;
        buflen = 0;
    }

    static u32 rotr(u32 x, int n) { return (x >> n) | (x << (32 - n)); }

    void block(const u8 *p) {
        u32 w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (u32(p[4 * i]) << 24) | (u32(p[4 * i + 1]) << 16) |
                   (u32(p[4 * i + 2]) << 8) | u32(p[4 * i + 3]);
        for (int i = 16; i < 64; i++) {
            u32 s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            u32 s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        u32 a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
            g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            u32 S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            u32 ch = (e & f) ^ (~e & g);
            u32 t1 = hh + S1 + ch + SHA_K[i] + w[i];
            u32 S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            u32 maj = (a & b) ^ (a & c) ^ (b & c);
            u32 t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const u8 *p, u64 n) {
        len += n;
        while (n > 0) {
            if (buflen == 0 && n >= 64) {
                block(p);
                p += 64;
                n -= 64;
            } else {
                int take = int(64 - buflen < (long long)n ? 64 - buflen : n);
                memcpy(buf + buflen, p, take);
                buflen += take;
                p += take;
                n -= take;
                if (buflen == 64) {
                    block(buf);
                    buflen = 0;
                }
            }
        }
    }

    void final(u8 out[32]) {
        u64 bitlen = len * 8;
        u8 pad = 0x80;
        update(&pad, 1);
        u8 z = 0;
        while (buflen != 56) update(&z, 1);
        u8 lb[8];
        for (int i = 0; i < 8; i++) lb[i] = u8(bitlen >> (56 - 8 * i));
        update(lb, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = u8(h[i] >> 24);
            out[4 * i + 1] = u8(h[i] >> 16);
            out[4 * i + 2] = u8(h[i] >> 8);
            out[4 * i + 3] = u8(h[i]);
        }
    }
};

static void sha256(const u8 *p, u64 n, u8 out[32]) {
    Sha256 s;
    s.init();
    s.update(p, n);
    s.final(out);
}

static void hmac_sha256(const u8 *key, int keylen, const u8 *m1, int n1,
                        const u8 *m2, int n2, const u8 *m3, int n3,
                        const u8 *m4, int n4, u8 out[32]) {
    u8 k[64];
    memset(k, 0, 64);
    if (keylen > 64) {
        sha256(key, keylen, k);
    } else {
        memcpy(k, key, keylen);
    }
    u8 ipad[64], opad[64];
    for (int i = 0; i < 64; i++) {
        ipad[i] = k[i] ^ 0x36;
        opad[i] = k[i] ^ 0x5c;
    }
    Sha256 s;
    s.init();
    s.update(ipad, 64);
    if (n1) s.update(m1, n1);
    if (n2) s.update(m2, n2);
    if (n3) s.update(m3, n3);
    if (n4) s.update(m4, n4);
    u8 inner[32];
    s.final(inner);
    s.init();
    s.update(opad, 64);
    s.update(inner, 32);
    s.final(out);
}

// ---------------------------------------------------------------------------
// 256-bit integers, little-endian limbs
// ---------------------------------------------------------------------------

struct U256 {
    u64 v[4];
};

static const U256 P = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                        0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
static const U256 NORD = {{0xBFD25E8CD0364141ULL, 0xBAAEDCE6AF48A03BULL,
                           0xFFFFFFFFFFFFFFFEULL, 0xFFFFFFFFFFFFFFFFULL}};
// 2^256 mod n (129 bits, 3 limbs)
static const u64 NC[3] = {0x402DA1732FC9BEBFULL, 0x4551231950B75FC4ULL, 1ULL};
static const u64 PK = 0x1000003D1ULL;  // 2^256 mod p (33 bits)

static void u256_from_be(U256 &r, const u8 b[32]) {
    for (int i = 0; i < 4; i++) {
        u64 w = 0;
        for (int j = 0; j < 8; j++) w = (w << 8) | b[8 * (3 - i) + j];
        r.v[i] = w;
    }
}

static void u256_to_be(u8 b[32], const U256 &a) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            b[8 * (3 - i) + j] = u8(a.v[i] >> (56 - 8 * j));
}

static bool u256_is_zero(const U256 &a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static bool u256_eq(const U256 &a, const U256 &b) {
    return a.v[0] == b.v[0] && a.v[1] == b.v[1] && a.v[2] == b.v[2] &&
           a.v[3] == b.v[3];
}

// -1, 0, 1
static int u256_cmp(const U256 &a, const U256 &b) {
    for (int i = 3; i >= 0; i--) {
        if (a.v[i] < b.v[i]) return -1;
        if (a.v[i] > b.v[i]) return 1;
    }
    return 0;
}

// r = a + b, returns carry
static u64 u256_add(U256 &r, const U256 &a, const U256 &b) {
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)a.v[i] + b.v[i];
        r.v[i] = (u64)c;
        c >>= 64;
    }
    return (u64)c;
}

// r = a - b, returns borrow
static u64 u256_sub(U256 &r, const U256 &a, const U256 &b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a.v[i] - b.v[i] - borrow;
        r.v[i] = (u64)d;
        borrow = (d >> 64) ? 1 : 0;
    }
    return (u64)borrow;
}

// t[8] = a * b
static void u256_mul_wide(u64 t[8], const U256 &a, const U256 &b) {
    memset(t, 0, 8 * sizeof(u64));
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)t[i + j] + (u128)a.v[i] * b.v[j] + carry;
            t[i + j] = (u64)cur;
            carry = cur >> 64;
        }
        t[i + 4] = (u64)carry;
    }
}

// ---------------------------------------------------------------------------
// Field arithmetic mod p
// ---------------------------------------------------------------------------

static void fe_reduce_once(U256 &a) {
    if (u256_cmp(a, P) >= 0) u256_sub(a, a, P);
}

static void fe_add(U256 &r, const U256 &a, const U256 &b) {
    u64 c = u256_add(r, a, b);
    if (c) {
        // r = r + 2^256 mod p = r + PK
        U256 k = {{PK, 0, 0, 0}};
        u256_add(r, r, k);
    }
    fe_reduce_once(r);
}

static void fe_sub(U256 &r, const U256 &a, const U256 &b) {
    u64 borrow = u256_sub(r, a, b);
    if (borrow) u256_add(r, r, P);
}

// reduce 512-bit t mod p using 2^256 ≡ PK
static void fe_reduce_wide(U256 &r, const u64 t[8]) {
    u64 m[5];
    u128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (u128)t[4 + i] * PK + t[i];
        m[i] = (u64)c;
        c >>= 64;
    }
    m[4] = (u64)c;  // < 2^34
    c = (u128)m[4] * PK + m[0];
    r.v[0] = (u64)c;
    c >>= 64;
    for (int i = 1; i < 4; i++) {
        c += m[i];
        r.v[i] = (u64)c;
        c >>= 64;
    }
    if (c) {  // one more 2^256 wrap
        U256 k = {{PK, 0, 0, 0}};
        u256_add(r, r, k);
    }
    fe_reduce_once(r);
}

static void fe_mul(U256 &r, const U256 &a, const U256 &b) {
    u64 t[8];
    u256_mul_wide(t, a, b);
    fe_reduce_wide(r, t);
}

static void fe_sqr(U256 &r, const U256 &a) { fe_mul(r, a, a); }

// r = a^(p-2) mod p  (Fermat inverse)
static void fe_inv(U256 &r, const U256 &a) {
    // p - 2
    U256 e = P;
    e.v[0] -= 2;
    U256 result = {{1, 0, 0, 0}};
    U256 base = a;
    for (int i = 0; i < 256; i++) {
        if ((e.v[i / 64] >> (i % 64)) & 1) fe_mul(result, result, base);
        fe_sqr(base, base);
    }
    r = result;
}

// ---------------------------------------------------------------------------
// Scalar arithmetic mod n
// ---------------------------------------------------------------------------

static void sc_reduce_once(U256 &a) {
    if (u256_cmp(a, NORD) >= 0) u256_sub(a, a, NORD);
}

// reduce a 512-bit value mod n by folding with 2^256 ≡ NC (129 bits)
static void sc_reduce_wide(U256 &r, const u64 tin[8]) {
    u64 t[8];
    memcpy(t, tin, sizeof(t));
    // Each fold: t = t_lo + t_hi * NC shrinks the high part by ~127 bits;
    // three folds bring 512 bits under 2^257.
    for (int pass = 0; pass < 3; pass++) {
        u64 hi[4] = {t[4], t[5], t[6], t[7]};
        if ((hi[0] | hi[1] | hi[2] | hi[3]) == 0) break;
        u64 prod[7];
        memset(prod, 0, sizeof(prod));
        for (int i = 0; i < 4; i++) {
            u128 carry = 0;
            for (int j = 0; j < 3; j++) {
                u128 cur = (u128)prod[i + j] + (u128)hi[i] * NC[j] + carry;
                prod[i + j] = (u64)cur;
                carry = cur >> 64;
            }
            prod[i + 3] += (u64)carry;
        }
        u128 c = 0;
        for (int i = 0; i < 7; i++) {
            c += (u128)prod[i] + (i < 4 ? t[i] : 0);
            t[i] = (u64)c;
            c >>= 64;
        }
        t[7] = (u64)c;
    }
    U256 res = {{t[0], t[1], t[2], t[3]}};
    // after folding, at most a 1-bit high word remains
    if (t[4] | t[5] | t[6] | t[7]) {
        u64 hi0 = t[4];
        u64 prod[4];
        u128 c = 0;
        for (int j = 0; j < 3; j++) {
            c += (u128)hi0 * NC[j];
            prod[j] = (u64)c;
            c >>= 64;
        }
        prod[3] = (u64)c;
        U256 add = {{prod[0], prod[1], prod[2], prod[3]}};
        u64 carry = u256_add(res, res, add);
        if (carry) {  // wrapped past 2^256: fold once more
            U256 nc = {{NC[0], NC[1], NC[2], 0}};
            u256_add(res, res, nc);
        }
    }
    sc_reduce_once(res);
    sc_reduce_once(res);
    r = res;
}

static void sc_mul(U256 &r, const U256 &a, const U256 &b) {
    u64 t[8];
    u256_mul_wide(t, a, b);
    sc_reduce_wide(r, t);
}

static void sc_add(U256 &r, const U256 &a, const U256 &b) {
    u64 c = u256_add(r, a, b);
    if (c) {
        U256 add = {{NC[0], NC[1], NC[2], 0}};
        u256_add(r, r, add);
    }
    sc_reduce_once(r);
}

// r = a^(n-2) mod n
static void sc_inv(U256 &r, const U256 &a) {
    U256 e = NORD;
    e.v[0] -= 2;
    U256 result = {{1, 0, 0, 0}};
    U256 base = a;
    for (int i = 0; i < 256; i++) {
        if ((e.v[i / 64] >> (i % 64)) & 1) sc_mul(result, result, base);
        sc_mul(base, base, base);
    }
    r = result;
}

// value mod n (for r = x mod n and e handling)
static void sc_from_u256(U256 &r, const U256 &a) {
    r = a;
    sc_reduce_once(r);
}

// ---------------------------------------------------------------------------
// Point arithmetic: Jacobian coordinates, curve y^2 = x^3 + 7 (a = 0)
// ---------------------------------------------------------------------------

struct Jac {
    U256 X, Y, Z;
    bool inf;
};

struct Aff {
    U256 x, y;
};

static const Aff G_AFF = {
    {{0x59F2815B16F81798ULL, 0x029BFCDB2DCE28D9ULL, 0x55A06295CE870B07ULL,
      0x79BE667EF9DCBBACULL}},
    {{0x9C47D08FFB10D4B8ULL, 0xFD17B448A6855419ULL, 0x5DA4FBFC0E1108A8ULL,
      0x483ADA7726A3C465ULL}}};

static void jac_set_inf(Jac &r) {
    memset(&r, 0, sizeof(r));
    r.inf = true;
}

static void jac_from_aff(Jac &r, const Aff &a) {
    r.X = a.x;
    r.Y = a.y;
    r.Z = {{1, 0, 0, 0}};
    r.inf = false;
}

// doubling, a = 0
static void jac_dbl(Jac &r, const Jac &p) {
    if (p.inf || u256_is_zero(p.Y)) {
        jac_set_inf(r);
        return;
    }
    U256 A, B, C, D, E, F, t;
    fe_sqr(A, p.X);              // A = X^2
    fe_sqr(B, p.Y);              // B = Y^2
    fe_sqr(C, B);                // C = B^2
    fe_add(t, p.X, B);
    fe_sqr(t, t);
    fe_sub(t, t, A);
    fe_sub(t, t, C);
    fe_add(D, t, t);             // D = 2((X+B)^2 - A - C)
    fe_add(E, A, A);
    fe_add(E, E, A);             // E = 3A
    fe_sqr(F, E);                // F = E^2
    U256 X3, Y3, Z3;
    fe_sub(X3, F, D);
    fe_sub(X3, X3, D);           // X3 = F - 2D
    fe_sub(t, D, X3);
    fe_mul(t, E, t);
    U256 c8;
    fe_add(c8, C, C);
    fe_add(c8, c8, c8);
    fe_add(c8, c8, c8);          // 8C
    fe_sub(Y3, t, c8);           // Y3 = E(D - X3) - 8C
    fe_mul(Z3, p.Y, p.Z);
    fe_add(Z3, Z3, Z3);          // Z3 = 2YZ
    r.X = X3;
    r.Y = Y3;
    r.Z = Z3;
    r.inf = false;
}

// general addition
static void jac_add(Jac &r, const Jac &p, const Jac &q) {
    if (p.inf) { r = q; return; }
    if (q.inf) { r = p; return; }
    U256 Z1Z1, Z2Z2, U1, U2, S1, S2, H, R;
    fe_sqr(Z1Z1, p.Z);
    fe_sqr(Z2Z2, q.Z);
    fe_mul(U1, p.X, Z2Z2);
    fe_mul(U2, q.X, Z1Z1);
    U256 t;
    fe_mul(t, q.Z, Z2Z2);
    fe_mul(S1, p.Y, t);
    fe_mul(t, p.Z, Z1Z1);
    fe_mul(S2, q.Y, t);
    fe_sub(H, U2, U1);
    fe_sub(R, S2, S1);
    if (u256_is_zero(H)) {
        if (u256_is_zero(R)) {
            jac_dbl(r, p);
        } else {
            jac_set_inf(r);
        }
        return;
    }
    U256 HH, HHH, V;
    fe_sqr(HH, H);
    fe_mul(HHH, HH, H);
    fe_mul(V, U1, HH);
    U256 X3, Y3, Z3;
    fe_sqr(X3, R);
    fe_sub(X3, X3, HHH);
    fe_sub(X3, X3, V);
    fe_sub(X3, X3, V);           // X3 = R^2 - H^3 - 2V
    fe_sub(t, V, X3);
    fe_mul(t, R, t);
    U256 s1hhh;
    fe_mul(s1hhh, S1, HHH);
    fe_sub(Y3, t, s1hhh);        // Y3 = R(V - X3) - S1 H^3
    fe_mul(Z3, p.Z, q.Z);
    fe_mul(Z3, Z3, H);
    r.X = X3;
    r.Y = Y3;
    r.Z = Z3;
    r.inf = false;
}

// mixed addition (q affine, Z2 = 1)
static void jac_add_aff(Jac &r, const Jac &p, const Aff &q) {
    Jac jq;
    jac_from_aff(jq, q);
    jac_add(r, p, jq);
}

static void jac_to_aff(Aff &r, const Jac &p) {
    U256 zi, zi2, zi3;
    fe_inv(zi, p.Z);
    fe_sqr(zi2, zi);
    fe_mul(zi3, zi2, zi);
    fe_mul(r.x, p.X, zi2);
    fe_mul(r.y, p.Y, zi3);
}

// ---------------------------------------------------------------------------
// Precomputed G table: odd/even multiples 1G..15G (affine) for 4-bit windows
// ---------------------------------------------------------------------------

static Aff G_TABLE[16];  // [i] = i*G, i in 1..15 ([0] unused)
static std::once_flag g_table_once;

// call_once, not a plain ready-flag: the Python side verifies batches
// OUTSIDE its core lock, so two gossip threads can race the first
// bt_verify_batch — an unsynchronized lazy init is a data race, and on
// weakly-ordered CPUs a reader could see the flag before the table.
static void init_g_table() {
    std::call_once(g_table_once, [] {
        Jac acc;
        jac_from_aff(acc, G_AFF);
        Jac cur = acc;
        for (int i = 1; i <= 15; i++) {
            jac_to_aff(G_TABLE[i], cur);
            Jac next;
            jac_add_aff(next, cur, G_AFF);
            cur = next;
        }
    });
}

// scalar * G using the affine table, 4-bit windows MSB-first
static void mul_base(Jac &r, const U256 &k) {
    init_g_table();
    jac_set_inf(r);
    for (int w = 63; w >= 0; w--) {
        if (!r.inf)
            for (int d = 0; d < 4; d++) jac_dbl(r, r);
        int limb = w / 16;
        int shift = (w % 16) * 4;
        int digit = int((k.v[limb] >> shift) & 0xF);
        if (digit) jac_add_aff(r, r, G_TABLE[digit]);
    }
}

// u1*G + u2*Q interleaved (Strauss-Shamir), 4-bit windows
static void mul_double(Jac &r, const U256 &u1, const U256 &u2, const Aff &q) {
    init_g_table();
    Jac qtab[16];  // [i] = i*Q, i in 1..15
    jac_from_aff(qtab[1], q);
    for (int i = 2; i <= 15; i++) jac_add_aff(qtab[i], qtab[i - 1], q);
    jac_set_inf(r);
    for (int w = 63; w >= 0; w--) {
        if (!r.inf)
            for (int d = 0; d < 4; d++) jac_dbl(r, r);
        int limb = w / 16;
        int shift = (w % 16) * 4;
        int d1 = int((u1.v[limb] >> shift) & 0xF);
        int d2 = int((u2.v[limb] >> shift) & 0xF);
        if (d1) jac_add_aff(r, r, G_TABLE[d1]);
        if (d2) jac_add(r, r, qtab[d2]);
    }
}

// ---------------------------------------------------------------------------
// ECDSA
// ---------------------------------------------------------------------------

// y^2 == x^3 + 7 (mod p)?  Inputs taken mod p, mirroring the Python oracle.
static bool on_curve(const U256 &x, const U256 &y) {
    U256 y2, x3, t;
    fe_sqr(y2, y);
    fe_sqr(t, x);
    fe_mul(x3, t, x);
    U256 seven = {{7, 0, 0, 0}};
    fe_add(x3, x3, seven);
    return u256_eq(y2, x3);
}

static bool verify_one(const u8 pub[64], const u8 msg[32], const u8 rs[64]) {
    U256 r, s;
    u256_from_be(r, rs);
    u256_from_be(s, rs + 32);
    // r, s in [1, n-1]
    if (u256_is_zero(r) || u256_is_zero(s)) return false;
    if (u256_cmp(r, NORD) >= 0 || u256_cmp(s, NORD) >= 0) return false;
    U256 x, y;
    u256_from_be(x, pub);
    u256_from_be(y, pub + 32);
    fe_reduce_once(x);
    fe_reduce_once(y);
    if (!on_curve(x, y)) return false;
    Aff q = {x, y};
    U256 e;
    u256_from_be(e, msg);
    U256 em;
    sc_from_u256(em, e);
    U256 w, u1, u2;
    sc_inv(w, s);
    sc_mul(u1, em, w);
    sc_mul(u2, r, w);
    Jac pt;
    if (u256_is_zero(u2)) {
        mul_base(pt, u1);
    } else {
        mul_double(pt, u1, u2, q);
    }
    if (pt.inf || u256_is_zero(pt.Z)) return false;
    // x(pt) mod n == r ?  Avoid inversion: X == r' * Z^2 for r' in
    // {r, r+n} (candidates < p).
    U256 z2;
    fe_sqr(z2, pt.Z);
    U256 cand = r;  // r < n < p
    for (int pass = 0; pass < 2; pass++) {
        U256 rhs;
        fe_mul(rhs, cand, z2);
        if (u256_eq(rhs, pt.X)) return true;
        // cand += n; stop if it overflows past p
        U256 next;
        u64 c = u256_add(next, cand, NORD);
        if (c || u256_cmp(next, P) >= 0) break;
        cand = next;
    }
    return false;
}

// RFC 6979 nonce (qlen = 256, HMAC-SHA256), matching
// babble_tpu/crypto/secp256k1.py::rfc6979_k
static void rfc6979_k(U256 &kout, const u8 priv[32], const u8 msg[32]) {
    U256 h1;
    u256_from_be(h1, msg);
    sc_reduce_once(h1);
    u8 h1b[32];
    u256_to_be(h1b, h1);
    u8 v[32], k[32];
    memset(v, 0x01, 32);
    memset(k, 0x00, 32);
    u8 zero = 0x00, one = 0x01;
    hmac_sha256(k, 32, v, 32, &zero, 1, priv, 32, h1b, 32, k);
    hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
    hmac_sha256(k, 32, v, 32, &one, 1, priv, 32, h1b, 32, k);
    hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
    for (;;) {
        hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
        U256 cand;
        u256_from_be(cand, v);
        if (!u256_is_zero(cand) && u256_cmp(cand, NORD) < 0) {
            kout = cand;
            return;
        }
        hmac_sha256(k, 32, v, 32, &zero, 1, nullptr, 0, nullptr, 0, k);
        hmac_sha256(k, 32, v, 32, nullptr, 0, nullptr, 0, nullptr, 0, v);
    }
}

static int sign_one(const u8 priv[32], const u8 msg_in[32], u8 rs_out[64]) {
    U256 d;
    u256_from_be(d, priv);
    if (u256_is_zero(d) || u256_cmp(d, NORD) >= 0) return 1;
    u8 msg[32];
    memcpy(msg, msg_in, 32);
    U256 e;
    u256_from_be(e, msg_in);
    U256 em;
    sc_from_u256(em, e);
    for (int tries = 0; tries < 64; tries++) {
        U256 k;
        rfc6979_k(k, priv, msg);
        Jac R;
        mul_base(R, k);
        Aff ra;
        jac_to_aff(ra, R);
        U256 r;
        sc_from_u256(r, ra.x);
        if (u256_is_zero(r)) {
            sha256(msg, 32, msg);  // rehash-and-retry, mirroring the oracle
            continue;
        }
        U256 kinv, rd, sum, s;
        sc_inv(kinv, k);
        sc_mul(rd, r, d);
        sc_add(sum, em, rd);
        sc_mul(s, kinv, sum);
        if (u256_is_zero(s)) {
            sha256(msg, 32, msg);
            continue;
        }
        u256_to_be(rs_out, r);
        u256_to_be(rs_out + 32, s);
        return 0;
    }
    return 2;
}

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

int bt_has_native(void) { return 1; }

// pub: n*64 bytes (x||y big-endian), msg: n*32, rs: n*64 (r||s), out: n bytes
//
// Large batches fan out over the hardware threads: Python releases the
// GIL for the ctypes call, so a whole sync's signatures verify on every
// core while the host thread is free — the per-signature EC math is
// embarrassingly parallel and signature-independent. Small batches stay
// serial (thread spawn costs more than the work below ~8 sigs/thread).
void bt_verify_batch(const u8 *pub, const u8 *msg, const u8 *rs, int n,
                     u8 *out) {
    if (n <= 0) return;
    init_g_table();  // concurrent callers race the lazy init otherwise
    int nthreads = int(std::thread::hardware_concurrency());
    if (nthreads < 1) nthreads = 1;
    if (nthreads > n / 8) nthreads = n / 8;  // >= 8 sigs per thread
    if (nthreads > 16) nthreads = 16;
    if (nthreads <= 1) {
        for (int i = 0; i < n; i++)
            out[i] = verify_one(pub + 64 * i, msg + 32 * i, rs + 64 * i)
                         ? 1 : 0;
        return;
    }
    std::vector<std::thread> workers;
    workers.reserve(nthreads);
    for (int t = 0; t < nthreads; t++) {
        int lo = int((long)n * t / nthreads);
        int hi = int((long)n * (t + 1) / nthreads);
        workers.emplace_back([=] {
            for (int i = lo; i < hi; i++)
                out[i] = verify_one(pub + 64 * i, msg + 32 * i, rs + 64 * i)
                             ? 1 : 0;
        });
    }
    for (auto &w : workers) w.join();
}

// returns 0 on success, nonzero on bad private key
int bt_sign(const u8 *priv, const u8 *msg, u8 *rs_out) {
    return sign_one(priv, msg, rs_out);
}

// out: 64 bytes x||y; returns 0 on success
int bt_pubkey(const u8 *priv, u8 *out) {
    U256 d;
    u256_from_be(d, priv);
    if (u256_is_zero(d) || u256_cmp(d, NORD) >= 0) return 1;
    Jac R;
    mul_base(R, d);
    Aff a;
    jac_to_aff(a, R);
    u256_to_be(out, a.x);
    u256_to_be(out + 32, a.y);
    return 0;
}

// batch SHA-256: n messages, each len bytes (fixed stride), out n*32
void bt_sha256_batch(const u8 *data, int stride, int n, u8 *out) {
    for (int i = 0; i < n; i++) sha256(data + (long)i * stride, stride, out + 32 * i);
}
}
